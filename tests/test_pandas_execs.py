"""Pandas exec family: mapInPandas / applyInPandas / grouped agg /
cogroup (reference sql-plugin .../execution/python/:
GpuMapInPandasExec.scala, GpuFlatMapGroupsInPandasExec.scala,
GpuAggregateInPandasExec.scala, GpuFlatMapCoGroupsInPandasExec.scala;
test model: udf_test.py + udf_cudf_test.py differential asserts)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.exec.python_exec import pandas_agg_udf
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.DoubleType(), True)])


def _df(s, n=60, parts=3, null_keys=False):
    rng = np.random.default_rng(7)
    k = rng.integers(0, 6, n).astype(np.int32)
    data = {"k": k, "v": rng.normal(size=n)}
    df = s.from_pydict(data, SCHEMA, partitions=parts)
    if null_keys:
        from spark_rapids_tpu.expr.conditional import If
        from spark_rapids_tpu.expr.core import Literal, lit
        df = df.select(
            If(col("k") >= lit(np.int32(5)),
               Literal(None, T.IntegerType()),
               col("k")).alias("k"), col("v"))
    return df


def _pandas_oracle(df):
    rows = df.collect()
    return pd.DataFrame({"k": pd.array([r[0] for r in rows],
                                       dtype="Int64"),
                         "v": [r[1] for r in rows]})


# -- map_in_pandas -----------------------------------------------------------

def test_map_in_pandas_device_matches_host():
    s = TpuSession({})
    out_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                           T.StructField("v2", T.DoubleType(), True)])

    def fn(it):
        for pdf in it:
            sub = pdf[pdf["v"] > 0]          # row count may change
            yield pd.DataFrame({"k": sub["k"], "v2": sub["v"] * 2})

    out = _df(s).map_in_pandas(fn, out_schema)
    assert "MapInPandasExec" in out.explain()
    dev = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert dev == host
    base = _pandas_oracle(_df(s))
    assert len(dev) == int((base["v"] > 0).sum())


def test_map_in_pandas_positional_columns():
    """Unlabeled (RangeIndex) output columns match the schema by
    position — Spark's assignment rule."""
    s = TpuSession({})
    out_schema = T.Schema([T.StructField("a", T.IntegerType(), True),
                           T.StructField("b", T.DoubleType(), True)])

    def fn(it):
        for pdf in it:
            out = pd.concat([pdf["k"], pdf["v"]], axis=1)
            out.columns = range(2)
            yield out

    rows = _df(s).map_in_pandas(fn, out_schema).collect()
    assert len(rows) == 60


def test_map_in_pandas_missing_column_fails():
    s = TpuSession({})
    out_schema = T.Schema([T.StructField("nope", T.DoubleType(), True)])

    def fn(it):
        for pdf in it:
            yield pd.DataFrame({"other": pdf["v"]})

    with pytest.raises(Exception, match="missing columns"):
        _df(s).map_in_pandas(fn, out_schema).collect()


def test_map_in_pandas_fallback_when_disabled():
    s = TpuSession({"spark.rapids.sql.exec.MapInPandasExec": "false"})
    out_schema = T.Schema([T.StructField("v2", T.DoubleType(), True)])

    def fn(it):
        for pdf in it:
            yield pd.DataFrame({"v2": pdf["v"] + 1})

    out = _df(s).map_in_pandas(fn, out_schema)
    text = out.explain()
    assert "! MapInPandasExec" in text
    assert "spark.rapids.sql.exec.MapInPandasExec is disabled" in text
    assert len(out.collect()) == 60


# -- apply_in_pandas ---------------------------------------------------------

def test_apply_in_pandas_matches_pandas_groupby():
    s = TpuSession({})
    out_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                           T.StructField("demeaned", T.DoubleType(), True),
                           T.StructField("n", T.LongType(), True)])

    def fn(pdf):
        return pd.DataFrame({"k": pdf["k"],
                             "demeaned": pdf["v"] - pdf["v"].mean(),
                             "n": len(pdf)})

    df = _df(s)
    out = df.group_by("k").apply_in_pandas(fn, out_schema)
    ex = out.explain()
    assert "FlatMapGroupsInPandasExec" in ex
    # groups must be clustered: the planner inserts a hash exchange
    assert "ShuffleExchangeExec" in ex
    got = sorted(out.collect())
    base = _pandas_oracle(df)
    want = []
    for k, g in base.groupby("k"):
        for v in g["v"]:
            want.append((int(k), v - g["v"].mean(), len(g)))
    assert len(got) == len(want)
    for a, b in zip(got, sorted(want)):
        assert a[0] == b[0] and abs(a[1] - b[1]) < 1e-9 and a[2] == b[2]


def test_apply_in_pandas_null_keys_form_a_group():
    s = TpuSession({})
    out_schema = T.Schema([T.StructField("n", T.LongType(), True)])

    def fn(pdf):
        return pd.DataFrame({"n": [len(pdf)]})

    df = _df(s, null_keys=True)
    got = sorted(r[0] for r in
                 df.group_by("k").apply_in_pandas(fn, out_schema).collect())
    base = _pandas_oracle(df)
    want = sorted(base.groupby("k", dropna=False).size().tolist())
    assert got == want
    # 6 groups: keys 0..4 plus the null group
    assert len(got) == 6


def test_apply_in_pandas_expression_key_rejected():
    s = TpuSession({})
    out_schema = T.Schema([T.StructField("n", T.LongType(), True)])
    with pytest.raises(NotImplementedError, match="plain column"):
        _df(s).group_by(col("k") + col("k")).apply_in_pandas(
            lambda p: pd.DataFrame({"n": [len(p)]}), out_schema)


# -- grouped aggregate pandas UDFs ------------------------------------------

def test_pandas_agg_udf_matches_oracle():
    s = TpuSession({})
    med = pandas_agg_udf(lambda v: v.median(), T.DoubleType())
    iqr = pandas_agg_udf(lambda v: v.quantile(0.75) - v.quantile(0.25),
                         T.DoubleType())
    df = _df(s)
    out = df.group_by("k").agg(med(col("v")).alias("med"),
                               iqr(col("v")).alias("iqr"))
    assert "AggregateInPandasExec" in out.explain()
    got = {r[0]: (r[1], r[2]) for r in out.collect()}
    base = _pandas_oracle(df)
    for k, g in base.groupby("k"):
        m, q = got[int(k)]
        assert abs(m - g["v"].median()) < 1e-9
        assert abs(q - (g["v"].quantile(0.75) -
                        g["v"].quantile(0.25))) < 1e-9


def test_pandas_agg_udf_grand_aggregate_single_row():
    s = TpuSession({})
    total = pandas_agg_udf(lambda v: float(v.sum()), T.DoubleType())
    df = _df(s)
    rows = df.agg(total(col("v")).alias("t")).collect()
    assert len(rows) == 1
    base = _pandas_oracle(df)
    assert abs(rows[0][0] - base["v"].sum()) < 1e-9


def test_pandas_agg_udf_mixed_with_builtin_rejected():
    from spark_rapids_tpu.expr.aggregates import Sum
    s = TpuSession({})
    m = pandas_agg_udf(lambda v: v.mean(), T.DoubleType())
    with pytest.raises(NotImplementedError, match="mixing"):
        _df(s).group_by("k").agg(m(col("v")).alias("a"),
                                 Sum(col("v")).alias("b"))


# -- cogroup -----------------------------------------------------------------

def test_cogroup_apply_in_pandas():
    s = TpuSession({})
    right_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                             T.StructField("w", T.DoubleType(), True)])
    # right side has keys 4..9: keys 0..3 left-only, 6..9 right-only
    right = s.from_pydict(
        {"k": np.arange(4, 10, dtype=np.int32),
         "w": np.arange(6, dtype=np.float64)}, right_schema, partitions=2)
    out_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                           T.StructField("nl", T.LongType(), True),
                           T.StructField("nr", T.LongType(), True)])

    def fn(l, r):
        assert list(l.columns) == ["k", "v"]      # full column sets,
        assert list(r.columns) == ["k", "w"]      # even when empty
        k = l["k"].iloc[0] if len(l) else r["k"].iloc[0]
        return pd.DataFrame({"k": [k], "nl": [len(l)], "nr": [len(r)]})

    df = _df(s)
    out = df.group_by("k").cogroup(right.group_by("k")).apply_in_pandas(
        fn, out_schema)
    assert "FlatMapCoGroupsInPandasExec" in out.explain()
    got = {r[0]: (r[1], r[2]) for r in out.collect()}
    base = _pandas_oracle(df)
    counts = base.groupby("k").size()
    assert set(got) == set(range(10))
    for k in range(10):
        nl = int(counts.get(k, 0))
        nr = 1 if 4 <= k <= 9 else 0
        assert got[k] == (nl, nr), k


def test_cogroup_key_arity_mismatch_rejected():
    s = TpuSession({})
    with pytest.raises(ValueError, match="same number of keys"):
        _df(s).group_by("k").cogroup(_df(s).group_by("k", "v"))


# -- review-finding regressions ---------------------------------------------

def test_chained_map_in_pandas_no_deadlock():
    """Three chained map_in_pandas with concurrentPythonWorkers=2: the
    streaming chain must consume ONE worker slot (reentrant hold), not
    one per level — holding a permit per level self-deadlocks."""
    s = TpuSession({"spark.rapids.python.concurrentPythonWorkers": "2"})
    sch = T.Schema([T.StructField("v", T.DoubleType(), True)])

    def step(delta):
        def fn(it):
            for pdf in it:
                yield pd.DataFrame({"v": pdf["v"] + delta})
        return fn

    out = _df(s).select(col("v")) \
        .map_in_pandas(step(1.0), sch) \
        .map_in_pandas(step(10.0), sch) \
        .map_in_pandas(step(100.0), sch)
    rows = out.collect()
    assert len(rows) == 60
    base = sorted(r[1] for r in _df(s).collect())
    assert sorted(r[0] for r in rows) == pytest.approx(
        [v + 111.0 for v in base])


def test_pandas_agg_udf_empty_input_grand_aggregate():
    """Keyless grouped-agg over empty input yields ONE row (the UDF sees
    empty Series) — Spark global-aggregation semantics."""
    from spark_rapids_tpu.expr.core import lit
    s = TpuSession({})
    total = pandas_agg_udf(lambda v: float(v.sum()), T.DoubleType())
    rows = _df(s).where(col("v") > lit(1e18)) \
        .agg(total(col("v")).alias("t")).collect()
    assert rows == [(0.0,)]


def test_cogroup_key_dtype_mismatch_rejected():
    """Hash routing is dtype-width-sensitive (murmur3): mismatched key
    types would silently split matching groups across partitions."""
    s = TpuSession({})
    other = s.from_pydict(
        {"k": np.arange(3, dtype=np.int64),
         "w": np.arange(3, dtype=np.float64)},
        T.Schema([T.StructField("k", T.LongType(), True),
                  T.StructField("w", T.DoubleType(), True)]))
    sch = T.Schema([T.StructField("n", T.LongType(), True)])
    with pytest.raises(TypeError, match="key types must match"):
        _df(s).group_by("k").cogroup(other.group_by("k")) \
            .apply_in_pandas(lambda l, r: pd.DataFrame({"n": [1]}), sch)


def test_cogroup_udf_mutating_empty_side_isolated():
    """A UDF that mutates its (absent-side) input must not corrupt
    later calls — each absent side receives a fresh copy."""
    s = TpuSession({})
    right = s.from_pydict(
        {"k": np.array([0], dtype=np.int32),
         "w": np.array([1.0])},
        T.Schema([T.StructField("k", T.IntegerType(), True),
                  T.StructField("w", T.DoubleType(), True)]), partitions=1)
    sch = T.Schema([T.StructField("k", T.IntegerType(), True),
                    T.StructField("ncols", T.LongType(), True)])

    def fn(l, r):
        k = l["k"].iloc[0] if len(l) else r["k"].iloc[0]
        n = len(r.columns)
        r["extra"] = 1          # mutate in place
        return pd.DataFrame({"k": [k], "ncols": [n]})

    out = _df(s).group_by("k").cogroup(right.group_by("k")) \
        .apply_in_pandas(fn, sch).collect()
    # every call saw the pristine 2-column right frame
    assert all(n == 2 for _, n in out)


# ---------------------------------------------------------------------------
# WindowInPandasExec (reference GpuWindowInPandasExec.scala:1-408)
# ---------------------------------------------------------------------------

def _window_df(s, n=48, null_keys=False):
    rng = np.random.default_rng(11)
    k = rng.integers(0, 4, n).astype(np.int32)
    t = rng.integers(0, 20, n).astype(np.int32)  # order key with peers
    data = {"k": k, "t": t, "v": rng.normal(size=n)}
    df = s.from_pydict(data, T.Schema([
        T.StructField("k", T.IntegerType(), True),
        T.StructField("t", T.IntegerType(), True),
        T.StructField("v", T.DoubleType(), True)]), partitions=3)
    if null_keys:
        from spark_rapids_tpu.expr.conditional import If
        from spark_rapids_tpu.expr.core import Literal, lit
        df = df.select(
            If(col("k") >= lit(np.int32(3)),
               Literal(None, T.IntegerType()), col("k")).alias("k"),
            col("t"), col("v"))
    return df


def _window_oracle(df, frame_fn):
    """Expected (k, t, v, w) rows: for each row, frame_fn(group_pdf, i)
    gives its [lo, hi) frame over the (k,t)-sorted group."""
    rows = df.collect()
    pdf = pd.DataFrame({"k": pd.array([r[0] for r in rows], dtype="Int64"),
                        "t": [r[1] for r in rows],
                        "v": [r[2] for r in rows]})
    pdf = pdf.sort_values(["k", "t"], kind="stable").reset_index(drop=True)
    out = []
    for _, g in pdf.groupby("k", dropna=False):
        g = g.reset_index(drop=True)
        for i in range(len(g)):
            lo, hi = frame_fn(g, i)
            out.append(float(g["v"].iloc[lo:hi].mean()))
    pdf["w"] = out
    return pdf


def _assert_window_matches(got_rows, want_pdf):
    got = sorted((r[0] if r[0] is not None else -99, r[1],
                  round(r[2], 9), round(r[3], 9)) for r in got_rows)
    want = sorted((int(k) if not pd.isna(k) else -99, int(t),
                   round(v, 9), round(w, 9))
                  for k, t, v, w in want_pdf.itertuples(index=False))
    assert got == want


@pytest.mark.parametrize("null_keys", [False, True])
def test_window_in_pandas_whole_partition(null_keys):
    from spark_rapids_tpu.exec.python_exec import pandas_window_udf
    from spark_rapids_tpu.expr.window import WindowSpec
    s = TpuSession({})
    df = _window_df(s, null_keys=null_keys)
    spec = WindowSpec(partition_by=(col("k"),))
    w = pandas_window_udf(lambda v: v.mean())(col("v")).over(spec)
    out = df.select(col("k"), col("t"), col("v"), w.alias("w"))
    want = _window_oracle(df, lambda g, i: (0, len(g)))
    _assert_window_matches(out.collect(), want)
    # the plan actually routed through WindowInPandasExec
    ov, meta = out._overridden(quiet=True)
    assert "WindowInPandasExec" in meta.exec_node.tree_string()


def test_window_in_pandas_rows_frame():
    from spark_rapids_tpu.exec.python_exec import pandas_window_udf
    from spark_rapids_tpu.expr.window import WindowFrame, WindowSpec
    s = TpuSession({})
    df = _window_df(s)
    # ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING
    spec = WindowSpec(partition_by=(col("k"),),
                      order_by=((col("t"), True),),
                      frame=WindowFrame("rows", -2, 1))
    w = pandas_window_udf(lambda v: v.mean())(col("v")).over(spec)
    out = df.select(col("k"), col("t"), col("v"), w.alias("w"))
    want = _window_oracle(
        df, lambda g, i: (max(i - 2, 0), min(i + 2, len(g))))
    _assert_window_matches(out.collect(), want)


def test_window_in_pandas_default_ordered_frame_includes_peers():
    from spark_rapids_tpu.exec.python_exec import pandas_window_udf
    from spark_rapids_tpu.expr.window import WindowSpec
    s = TpuSession({})
    df = _window_df(s)
    # default frame with order_by = RANGE UNBOUNDED..CURRENT ROW: the
    # frame extends through the END of the current row's peer group
    spec = WindowSpec(partition_by=(col("k"),),
                      order_by=((col("t"), True),))
    w = pandas_window_udf(lambda v: v.mean())(col("v")).over(spec)
    out = df.select(col("k"), col("t"), col("v"), w.alias("w"))

    def frame(g, i):
        t = g["t"].iloc[i]
        return 0, int((g["t"] <= t).sum())

    want = _window_oracle(df, frame)
    _assert_window_matches(out.collect(), want)


def test_window_in_pandas_global_window_and_multi_udf_inputs():
    from spark_rapids_tpu.exec.python_exec import pandas_window_udf
    from spark_rapids_tpu.expr.window import WindowFrame, WindowSpec
    s = TpuSession({})
    df = _window_df(s, n=20)
    # empty partition-by: one global group (reference logs the same
    # single-partition warning and proceeds)
    spec = WindowSpec(order_by=((col("t"), True),),
                      frame=WindowFrame("rows", None, 0))
    w = pandas_window_udf(
        lambda v, t: float((v * t).sum()))(col("v"), col("t")).over(spec)
    out = df.select(col("t"), col("v"), w.alias("w")).collect()
    rows = df.collect()
    pdf = pd.DataFrame({"t": [r[1] for r in rows],
                        "v": [r[2] for r in rows]})
    pdf = pdf.sort_values("t", kind="stable").reset_index(drop=True)
    want = [float((pdf["v"].iloc[:i + 1] * pdf["t"].iloc[:i + 1]).sum())
            for i in range(len(pdf))]
    got = sorted((r[0], round(r[1], 9), round(r[2], 9)) for r in out)
    wantrows = sorted((int(t), round(v, 9), round(wv, 9)) for t, v, wv in
                      zip(pdf["t"], pdf["v"], want))
    assert got == wantrows

"""Physical-plan invariant verifier (plan/verify.py).

Two halves, per the static-analysis tentpole contract:

- every TPC-H ladder plan verifies CLEAN through all rewrite passes —
  single-chip and mesh-8, fusion and AQE on and off (run under
  ``everyPass`` so the verifier fires inside ``prepare()`` after each
  pass and a violation aborts planning at the pass that caused it);
- hand-broken plans (schema mismatch on a pass-through node, a
  donate_ok fused stage over a shared input, a stripped lineage stamp,
  a host transition captured inside a mesh region) each raise a
  :class:`PlanInvariantError` naming the RIGHT node path and the pass
  after which the broken shape was observed.
"""
import numpy as np
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.plan.verify import (PASS_ORDER, PlanInvariantError,
                                          verify_plan)

_LADDER = ["q1", "q3", "q6", "q12", "q13", "q18"]

# every ladder conf turns everyPass on so the suite exercises the
# per-pass attribution mode end to end (the default steady-state mode
# verifies once, after the final pass — pinned separately below)
_EVERY = {"spark.rapids.sql.verify.plan.everyPass": True}

_CONFS = {
    "single": {**_EVERY},
    "mesh8": {**_EVERY, "spark.rapids.tpu.mesh.deviceCount": 8},
    "fusion_off": {**_EVERY, "spark.rapids.sql.fusion.enabled": False},
    "aqe": {**_EVERY,
            "spark.sql.adaptive.shuffledHashJoin.enabled": True},
    "mesh8_aqe": {**_EVERY, "spark.rapids.tpu.mesh.deviceCount": 8,
                  "spark.sql.adaptive.shuffledHashJoin.enabled": True},
}

SCHEMA = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    d = str(tmp_path_factory.mktemp("tpch_verify") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


def _plan(df):
    ov, meta = df._overridden(quiet=True)
    return meta.exec_node


def _find(node, name, seen=None):
    seen = set() if seen is None else seen
    if id(node) in seen:
        return None
    seen.add(id(node))
    if type(node).__name__ == name:
        return node
    for c in node.children:
        hit = _find(c, name, seen)
        if hit is not None:
            return hit
    return None


def _pydict_plan(conf=None):
    """filter -> project -> group_by over 4 partitions: the smallest
    plan carrying a FusedStageExec AND a ShuffleExchangeExec (or, under
    mesh confs, a MeshRegionExec) — SF0.01 TPC-H scans are
    single-partition and plan no exchange at all."""
    s = TpuSession(dict(conf or {}))
    data = {"k": (np.arange(40) % 5).astype(np.int32),
            "v": np.arange(40, dtype=np.int64)}
    df = (s.from_pydict(data, SCHEMA, partitions=4)
            .filter(col("v") > lit(3))
            .select(col("k"), (col("v") * lit(2)).alias("w"))
            .group_by("k").agg(Sum(col("w"))))
    return _plan(df), s


# ---------------------------------------------------------------------------
# clean plans: every ladder query under every conf verifies through
# prepare()'s per-pass hooks AND an explicit final walk
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("confname", sorted(_CONFS))
@pytest.mark.parametrize("query", _LADDER)
def test_tpch_plans_verify_clean(data_dir, query, confname):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    s = TpuSession(dict(_CONFS[confname]))
    df = build_tpch_query(query, s, data_dir)
    plan = _plan(df)  # prepare() already verified after every pass
    verify_plan(plan, s.conf)  # and the final shape re-verifies


def test_pydict_plans_verify_clean():
    for confname in sorted(_CONFS):
        plan, s = _pydict_plan(_CONFS[confname])
        verify_plan(plan, s.conf)


def _trace_verify_calls(monkeypatch):
    from spark_rapids_tpu.plan import verify as V
    calls = []
    real = V.verify_plan
    monkeypatch.setattr(
        V, "verify_plan",
        lambda root, conf=None, pass_name="mesh_regions":
            (calls.append(pass_name), real(root, conf, pass_name))[1])
    return calls


def test_every_pass_mode_verifies_after_every_pass(monkeypatch):
    """Under everyPass, prepare() verifies once per rewrite pass, in
    PASS_ORDER (sans the runtime-only aqe_replan hook)."""
    calls = _trace_verify_calls(monkeypatch)
    _pydict_plan(_EVERY)
    assert tuple(calls) == PASS_ORDER[:-1]


def test_default_mode_verifies_final_plan_once(monkeypatch):
    """Default steady state: one walk, after the final rewrite pass —
    the <2% plan-time budget that keeps the verifier on everywhere."""
    calls = _trace_verify_calls(monkeypatch)
    _pydict_plan()
    assert calls == ["mesh_regions"]


def test_verifier_conf_gate_off(monkeypatch):
    from spark_rapids_tpu.plan import verify as V
    calls = []
    monkeypatch.setattr(V, "verify_plan",
                        lambda *a, **k: calls.append(a))
    _pydict_plan({"spark.rapids.sql.verify.plan": False})
    assert calls == []


# ---------------------------------------------------------------------------
# broken plans: each hand-introduced violation names node and pass
# ---------------------------------------------------------------------------

def test_schema_mismatch_on_passthrough_node():
    from spark_rapids_tpu.exec.transitions import BackendSwitchExec

    class _BadSwitch(BackendSwitchExec):
        """Pass-through that silently drops its child's last field."""
        @property
        def output_schema(self):
            full = self.children[0].output_schema
            return T.Schema(list(full.fields[:-1]))

    plan, s = _pydict_plan()
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(_BadSwitch(plan, "device"), s.conf, "transitions")
    e = ei.value
    assert e.pass_name == "transitions"
    assert e.node_path.startswith("_BadSwitch")
    assert "diverges" in e.message


def test_double_consumer_donation():
    from spark_rapids_tpu.exec.basic import GlobalLimitExec
    from spark_rapids_tpu.exec.core import PlanNode

    class _Tee(PlanNode):
        """Test-only 2-parent shape: both children share a subtree."""
        @property
        def output_schema(self):
            return self.children[0].output_schema

    plan, s = _pydict_plan()
    fused = _find(plan, "FusedStageExec")
    assert fused is not None
    # second consumer of the fused stage's input -> donation illegal
    root = _Tee([plan, GlobalLimitExec(1, fused.children[0])])
    fused.donate_ok = True
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(root, s.conf, "fusion")
    e = ei.value
    assert e.pass_name == "fusion"
    assert "FusedStageExec" in e.node_path
    assert "non-exclusive" in e.message


def test_stripped_lineage_stamp():
    plan, s = _pydict_plan()
    ex = _find(plan, "ShuffleExchangeExec")
    assert ex is not None and getattr(ex, "_conf_fp", None)
    ex._conf_fp = None
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(plan, s.conf, "stamp_lineage")
    e = ei.value
    assert e.pass_name == "stamp_lineage"
    assert e.node_path.endswith("ShuffleExchangeExec[0]")
    assert "lineage stamp" in e.message
    # before the stamping pass ran, the same shape is legal
    verify_plan(plan, s.conf, "shared_scans")


def test_transition_captured_inside_mesh_region():
    from spark_rapids_tpu.exec.transitions import BackendSwitchExec
    plan, s = _pydict_plan({"spark.rapids.tpu.mesh.deviceCount": 8})
    region = _find(plan, "MeshRegionExec")
    assert region is not None
    verify_plan(plan, s.conf)  # sane before the breakage
    region._members = region._members + (
        BackendSwitchExec(region._members[-1], "host"),)
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(plan, s.conf, "mesh_regions")
    e = ei.value
    assert e.pass_name == "mesh_regions"
    assert "MeshRegionExec" in e.node_path
    assert "host transition" in e.message


def test_error_is_structured():
    plan, s = _pydict_plan()
    ex = _find(plan, "ShuffleExchangeExec")
    ex._conf_fp = None
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(plan, s.conf)
    e = ei.value
    # message embeds both structured fields, for log triage
    assert e.node_path in str(e) and "mesh_regions" in str(e)
    assert isinstance(e, RuntimeError)


# ---------------------------------------------------------------------------
# join/window members and chained-region edges (ISSUE 14)
# ---------------------------------------------------------------------------

def _join_region_plan(data_dir):
    """q12 under mesh-8: its joins absorb into a region, so the plan
    carries a MeshRegionExec with at least one MeshJoinExec member and
    a build-subtree child per join."""
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    s = TpuSession({**_EVERY, "spark.rapids.tpu.mesh.deviceCount": 8})
    plan = _plan(build_tpch_query("q12", s, data_dir))
    region = None
    def walk(n, seen):
        nonlocal region
        if id(n) in seen:
            return
        seen.add(id(n))
        if type(n).__name__ == "MeshRegionExec" and \
                any(type(m).__name__ == "MeshJoinExec"
                    for m in n._members):
            region = n
        for c in n.children:
            walk(c, seen)
    walk(plan, set())
    return plan, region, s


def test_join_region_verifies_clean_under_every_pass(data_dir):
    # prepare() under everyPass already verified after every pass; the
    # final walk re-verifies the join-bearing region shape explicitly
    plan, region, s = _join_region_plan(data_dir)
    assert region is not None, "q12 mesh-8 formed no join-bearing region"
    verify_plan(plan, s.conf)


def test_broken_join_build_edge_in_region(data_dir):
    from spark_rapids_tpu.exec.basic import GlobalLimitExec
    plan, region, s = _join_region_plan(data_dir)
    assert region is not None
    # wedge a node between the region's build child and the absorbed
    # join's own build link: the identities diverge
    region.children = (region.children[0],
                       GlobalLimitExec(1, region.children[1]),
                       *region.children[2:])
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(plan, s.conf, "mesh_regions")
    e = ei.value
    assert e.pass_name == "mesh_regions"
    assert "build edge" in e.message


def test_window_region_verifies_clean_under_every_pass():
    from spark_rapids_tpu.expr.window import (RowNumber, WindowExpression,
                                              WindowSpec)
    s = TpuSession({**_EVERY, "spark.rapids.tpu.mesh.deviceCount": 8})
    data = {"k": (np.arange(40) % 5).astype(np.int32),
            "v": np.arange(40, dtype=np.int64)}
    spec = WindowSpec((col("k"),), ((col("v"), True),))
    df = (s.from_pydict(data, SCHEMA, partitions=4)
            .filter(col("v") > lit(3))
            .select(col("k"),
                    WindowExpression(RowNumber(), spec).alias("rn")))
    plan = _plan(df)  # everyPass verified inside prepare()
    region = _find(plan, "MeshRegionExec")
    assert region is not None
    assert type(region._terminal).__name__ == "MeshWindowExec"
    verify_plan(plan, s.conf)


def test_chained_region_edge_crossing_meshes_rejected():
    s = TpuSession({**_EVERY, "spark.rapids.tpu.mesh.deviceCount": 8})
    data = {"k": (np.arange(40) % 5).astype(np.int32),
            "v": np.arange(40, dtype=np.int64)}
    df = (s.from_pydict(data, SCHEMA, partitions=4)
            .repartition(8, col("k"))
            .filter(col("v") > lit(3))
            .group_by("k").agg(Sum(col("v"))))
    plan = _plan(df)
    region = _find(plan, "MeshRegionExec")
    assert region is not None
    leaf = region.children[0]
    assert type(leaf).__name__ == "MeshExchangeExec"
    verify_plan(plan, s.conf)  # sane before the breakage
    leaf.mesh_size = 4  # upstream exchange now serves a different mesh
    with pytest.raises(PlanInvariantError) as ei:
        verify_plan(plan, s.conf, "mesh_regions")
    e = ei.value
    assert e.pass_name == "mesh_regions"
    assert "chained region edge crosses meshes" in e.message

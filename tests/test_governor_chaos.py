"""Concurrent-query memory chaos: TPC-H racing under the governor.

The single-query OOM chaos suite (test_oom_chaos.py) proves the
split-and-retry ladder; this suite adds the cross-query dimension the
memory governor exists for: several TPC-H queries share ONE session —
one process-wide governor, one admission controller — under a tiny
spill store and a deterministic HBM-exhaustion storm.  Required
outcomes: every query stays EXACT against its host oracle, wall time
stays bounded (no eviction livelock between concurrent retry ladders),
the governor's per-query ledgers stay internally consistent while the
race runs, and nothing — bytes or grant reservations — leaks once the
queries drain.
"""
import threading
import time

import pytest

from spark_rapids_tpu.bench.runner import _rows_match
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.memory.governor import get_governor
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

# storm threshold low enough that even the smaller customer/orders
# scans (q13) split, not just lineitem; 32-row minSplitRows floor keeps
# convergence guaranteed
_STORM = "memory.oom.until_rows:oom,until_rows=8192"
_CHAOS_CONF = {
    "spark.rapids.test.faults": _STORM,
    "spark.rapids.memory.host.spillStorageSize": 64 << 20,
    "spark.rapids.sql.admission.maxConcurrentQueries": 4,
}

#: must include the build-heavy join queries (q13 customer⟕orders,
#: q18 large IN-subquery join) alongside the wide aggregate q1
_QUERIES = ["q1", "q13", "q18"]

_WALL_LIMIT_S = 420.0


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_gov_chaos") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


def _oracle(df):
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = df._overridden(quiet=True)
    return collect_host(meta.exec_node, df._s.conf)


def test_concurrent_queries_exact_under_storm(data_dir):
    session = TpuSession(dict(_CHAOS_CONF))
    gov = get_governor()
    # the governor is a process singleton: earlier test files may have
    # leaked still-referenced ledgers of their own — leak checks below
    # are scoped to what THIS test registers
    pre_registered = set(gov.query_stats())
    before = get_registry().snapshot()["counters"]
    dfs = {q: build_tpch_query(q, session, data_dir) for q in _QUERIES}
    oracles = {q: _oracle(df) for q, df in dfs.items()}

    results: dict = {}
    errors: dict = {}

    def run(q):
        try:
            results[q] = dfs[q].collect()
        except Exception as ex:  # noqa: BLE001 - recorded and asserted below
            errors[q] = ex

    # ledger sampler: while the race runs, every registered query's
    # ledger must stay internally consistent (device/pinned/peak
    # relations) — grant reservations are legitimate mid-run, so only
    # the per-query invariants are checked here
    stop = threading.Event()
    max_registered = [0]
    ledger_violations: list = []

    def sample():
        while not stop.is_set():
            stats = gov.query_stats()
            max_registered[0] = max(max_registered[0], len(stats))
            for qid, s in stats.items():
                if (s["device_bytes"] < 0 or s["pinned_bytes"] < 0
                        or s["pinned_bytes"] > s["device_bytes"]
                        or s["peak_bytes"] < s["device_bytes"]):
                    ledger_violations.append((qid, dict(s)))
            time.sleep(0.01)

    sampler = threading.Thread(target=sample, daemon=True)
    threads = [threading.Thread(target=run, args=(q,), daemon=True)
               for q in _QUERIES]
    t0 = time.monotonic()
    sampler.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(_WALL_LIMIT_S - (time.monotonic() - t0))
    wall = time.monotonic() - t0
    stuck = [t for t in threads if t.is_alive()]
    stop.set()
    sampler.join(5.0)
    assert not stuck, (f"livelock: {len(stuck)} queries still running "
                       f"after {wall:.0f}s")
    assert wall < _WALL_LIMIT_S
    assert not errors, errors
    assert not ledger_violations, ledger_violations[:3]
    assert max_registered[0] >= 2, \
        "queries never actually overlapped; chaos was vacuous"
    for q in _QUERIES:
        assert _rows_match(results[q], oracles[q]), f"{q} inexact"

    # the storm actually fired and the governed reclaim path ran
    moved = get_registry().delta({"counters": before})["counters"]
    assert moved.get("faults.injected.memory.oom.until_rows", 0) > 0
    assert moved.get("governor_reclaims", 0) > 0

    # nothing leaks once the queries drain: no registered ledgers, no
    # outstanding reservations (verifier also covers the relations)
    session.shutdown(drain=True)
    import gc
    gc.collect()    # unclosed-but-unreferenced catalogs drop their ledgers
    from spark_rapids_tpu.plan.verify import verify_governor_ledger
    assert set(gov.query_stats()) <= pre_registered, \
        "this test's queries leaked governor ledgers after drain"
    assert gov.reserved_bytes() == 0
    verify_governor_ledger(gov)


def test_oom_storm_denial_converges(data_dir):
    """memory.governor.oom_storm makes every arbitration report zero
    bytes freed — spilling 'cannot keep up' — so correctness must come
    from the split ladder alone, still exact and bounded."""
    conf = dict(_CHAOS_CONF)
    conf["spark.rapids.test.faults"] = (
        _STORM + ";memory.governor.oom_storm:oom,times=0")
    session = TpuSession(conf)
    df = build_tpch_query("q1", session, data_dir)
    want = _oracle(df)
    before = get_registry().snapshot()["counters"]
    t0 = time.monotonic()
    got = df.collect()
    assert time.monotonic() - t0 < _WALL_LIMIT_S
    assert _rows_match(got, want)
    moved = get_registry().delta({"counters": before})["counters"]
    assert moved.get("governor_storm_denials", 0) > 0
    session.shutdown(drain=True)


def test_cancel_during_grant_stall_releases_reservation():
    """memory.grant.stall holds a reclaim in the grant-wait window; a
    cancel landing there must unwind with the terminal error, leaving
    no reservation behind (the leak the premerge gate checks)."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.lifecycle import QueryCancelled, QueryLifecycle
    from spark_rapids_tpu.memory import BufferCatalog
    from spark_rapids_tpu.memory.governor import MemoryGovernor

    gov = MemoryGovernor()
    try:
        conf = TpuConf({"spark.rapids.test.faults":
                        "memory.grant.stall:stall,seconds=30"})
        older = BufferCatalog(device_limit=1000, host_limit=1 << 20)
        younger = BufferCatalog(device_limit=1000, host_limit=1 << 20,
                                conf=conf)
        lc = QueryLifecycle("young")
        lc.start()
        # tiny minSpill floor: with the default 16m floor the need could
        # never fit under the toy 1000-byte budget and the wait would be
        # (correctly) skipped instead of parking in the stall window
        knobs = {"spark.rapids.memory.governor.minSpillBytes": 1}
        gov.register(older, "old", None, knobs)
        gov.register(younger, "young", lc, knobs)
        # over-commit the ledger so the reclaim genuinely parks: the
        # OLDER query holds nearly everything and is off-limits to the
        # younger requester (wound-wait), whose own catalog is empty
        gov.account(older, 990)
        err = []

        def run():
            try:
                gov.reclaim(younger, 500)
            except QueryCancelled as ex:
                err.append(ex)

        t = threading.Thread(target=run, daemon=True)
        t0 = time.monotonic()
        t.start()
        deadline = time.monotonic() + 5.0
        while (younger.faults.fired_count("memory.grant.stall") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert younger.faults.fired_count("memory.grant.stall") == 1, \
            "stall fault never fired; the wait window was not entered"
        lc.cancel("chaos cancel")
        t.join(10.0)
        assert not t.is_alive(), "cancel did not break the stalled wait"
        assert time.monotonic() - t0 < 31.0, "waited out the full stall"
        assert err, "terminal error swallowed by the grant wait"
        assert gov.reserved_bytes() == 0, "reservation leaked"
        gov.account(older, -990)
        older.close()
        younger.close()
    finally:
        with gov._cond:
            gov._stop_bg_locked()
        # hand the shared source name back to the process singleton so
        # later suite files still see governor.* gauges
        from spark_rapids_tpu.memory import governor as gov_mod
        if gov_mod._GOVERNOR is not None:
            get_registry().register_source(
                "governor", gov_mod._GOVERNOR._source)
        else:
            get_registry().unregister_source("governor")

"""Cost-attribution plane: operator/device profiler, HBM occupancy
timeline, per-tenant metering, live progress (obs/profile.py +
obs/metering.py) and the tools.history forensics over their output.

Covers the plane's contracts, not just happy paths:

* fused-stage / mesh-region time is attributed to member ops as child
  rows that never double-count in top-level sums;
* the per-query artifact validates against ci/obs_schema.json (the
  same check ci/premerge.sh runs on a real q3@mesh-8 export);
* the two accounting paths (per-tenant charges vs. instrumentation
  totals) conserve, and the cross-check catches books that DON'T;
* worker drain/merge deltas move tenant charges exactly once;
* the profiler is inert when disabled (ExecCtx.profiler is None) —
  the stronger sys.modules guarantee needs a fresh interpreter and is
  enforced by ci/premerge.sh;
* Prometheus label escaping survives hostile tenant names, and
  histogram snapshot merges are exact under scrape-while-observe.
"""
import json
import threading

import pytest

from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.obs.metering import USAGE_METRICS, TenantMeter
from spark_rapids_tpu.obs.profile import (ProfileStore, QueryProfiler,
                                          live_progress)
from spark_rapids_tpu.obs.registry import (Histogram, MetricsRegistry,
                                           get_registry,
                                           merge_histogram_snapshots)

PROF_CONF = {"spark.rapids.obs.profile.enabled": "true"}


def _conf(extra=None):
    return TpuConf(dict(PROF_CONF, **(extra or {})))


class _FusedNode:
    """Stand-in for FusedStageExec: a container exposing fused_ops."""

    def __init__(self, members):
        self.fused_ops = tuple(members)


class _Leaf:
    pass


class _LeafA:
    pass


class _LeafB:
    pass


# ---------------------------------------------------------------------------
# operator profiler: attribution + artifact
# ---------------------------------------------------------------------------

def test_member_attribution_splits_container_time():
    prof = QueryProfiler("q-attr", _conf())
    node = _FusedNode([_LeafA(), _LeafB()])
    prof.record_op(node, "FusedStageExec#1", active_s=0.4, wall_s=0.5,
                   batches=2, rows=100, partition=0)
    ops = prof.operators()
    top = {k: e for k, e in ops.items() if e["parent"] is None}
    kids = {k: e for k, e in ops.items() if e["parent"]}
    assert list(top) == ["FusedStageExec#1"]
    assert len(kids) == 2
    # equal split, and the member sum never exceeds the container
    for e in kids.values():
        assert e["parent"] == "FusedStageExec#1"
        assert e["device_s"] == pytest.approx(0.2)
    assert sum(e["device_s"] for e in kids.values()) <= \
        top["FusedStageExec#1"]["device_s"] + 1e-9
    # top-level device_seconds counts the container once, members never
    assert prof.device_seconds() == pytest.approx(0.4)


def test_flamegraph_members_not_double_counted():
    prof = QueryProfiler("q-flame", _conf())
    prof.record_op(_Leaf(), "ScanExec#0", 0.1, 0.1, 1, 10, 0)
    prof.record_op(_FusedNode([_Leaf()]), "FusedStageExec#1",
                   0.2, 0.2, 1, 10, 0)
    text = prof.flamegraph()
    lines = [ln for ln in text.splitlines() if ln]
    # every line is "frame[;frame] value-in-us"
    total_us = 0
    for ln in lines:
        stack, val = ln.rsplit(" ", 1)
        assert stack.startswith("q-flame;")
        total_us += int(val)
    # container frames with members contribute ONLY via member lines
    assert not any(ln.rsplit(" ", 1)[0].endswith("FusedStageExec#1")
                   for ln in lines)
    assert total_us == pytest.approx((0.1 + 0.2) * 1e6, rel=0.01)


def test_artifact_validates_against_checked_in_schema():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    from validate_obs import load_schema, validate
    prof = QueryProfiler("q-schema", _conf())
    prof.record_op(_FusedNode([_Leaf(), _Leaf()]), "FusedStageExec#2",
                   0.3, 0.4, 3, 42, 1)
    art = prof.artifact()
    assert validate(art, load_schema("profile")) == []
    assert art["kind"] == "profile" and art["query_id"] == "q-schema"
    blob = prof.history_blob()
    assert validate(blob, load_schema("history")["properties"]
                    ["profile"]) == []


def test_profiler_op_table_is_bounded():
    prof = QueryProfiler("q-bound", _conf(
        {"spark.rapids.obs.profile.maxOps": "8"}))
    for i in range(50):
        prof.record_op(_Leaf(), f"ProjectExec#{i}", 0.001, 0.001, 1, 1, 0)
    ops = prof.operators()
    assert len(ops) <= 9  # 8 + the "(other)" overflow row
    assert "(other)" in ops
    # overflow still conserves: nothing dropped from the total
    assert prof.device_seconds() == pytest.approx(0.05)


def test_profile_store_keeps_per_fingerprint_tables():
    store = ProfileStore(max_fingerprints=2)
    store.note("fp-a", {"X": {"op": "X", "device_s": 1.0}}, wall_s=1.0)
    store.note("fp-b", {"Y": {"op": "Y", "device_s": 2.0}}, wall_s=2.0)
    store.note("fp-c", {"Z": {"op": "Z", "device_s": 3.0}}, wall_s=3.0)
    snap = store.snapshot()
    assert "fp-a" not in snap  # LRU-evicted
    assert set(snap) == {"fp-b", "fp-c"}


# ---------------------------------------------------------------------------
# per-tenant metering + conservation
# ---------------------------------------------------------------------------

def test_conservation_holds_and_detects_broken_books():
    m = TenantMeter()
    # charge path and instrumentation path agree
    m.charge("etl", "fp1", {"device_seconds": 1.0, "queries": 1})
    m.charge("web", "fp2", {"device_seconds": 3.0, "queries": 1})
    m.add_total("device_seconds", 4.0)
    get_registry().inc("queries_executed", 2)
    cons = m.conservation()
    assert cons["ok"], cons
    assert cons["device_seconds"]["tenants_sum"] == pytest.approx(4.0)
    # now break the instrumentation side by >5%: the cross-check trips
    m.add_total("device_seconds", 1.0)
    cons = m.conservation()
    assert not cons["ok"]
    assert not cons["device_seconds"]["ok"]
    # a tighter tolerance flags what a loose one forgives
    m2 = TenantMeter()
    m2.charge("t", None, {"device_seconds": 1.0})
    m2.add_total("device_seconds", 1.04)
    assert m2.conservation(tolerance=0.05)["ok"]
    assert not m2.conservation(tolerance=0.01)["ok"]


def test_meter_snapshot_tracks_tenant_and_fingerprint():
    m = TenantMeter()
    m.charge("etl", "fp1", {"device_seconds": 0.5, "scan_bytes": 100})
    m.charge("etl", "fp1", {"device_seconds": 0.5, "scan_bytes": 100})
    snap = m.snapshot()
    assert snap["tenants"]["etl"]["device_seconds"] == pytest.approx(1.0)
    assert snap["tenants"]["etl"]["scan_bytes"] == pytest.approx(200)
    assert snap["fingerprints"]["fp1"]["device_seconds"] == \
        pytest.approx(1.0)
    assert set(snap) >= {"tenants", "fingerprints", "totals", "workers"}


def test_drain_merge_moves_charges_exactly_once():
    worker, driver = TenantMeter(), TenantMeter()
    worker.charge("etl", "fp1", {"device_seconds": 2.0})
    d1 = worker.drain_delta()
    assert d1 is not None
    assert d1["tenants"]["etl"]["device_seconds"] == pytest.approx(2.0)
    # nothing new moved: the next drain is empty, not a re-ship
    assert worker.drain_delta() is None
    worker.charge("etl", "fp1", {"device_seconds": 0.5})
    d2 = worker.drain_delta()
    assert d2["tenants"]["etl"]["device_seconds"] == pytest.approx(0.5)
    for d in (d1, d2):
        driver.merge_delta({"tenants": d["tenants"]})
    assert driver.snapshot()["tenants"]["etl"]["device_seconds"] == \
        pytest.approx(2.5)
    # worker totals land under the per-worker ledger, NOT the driver's
    # own conservation books
    driver.ingest_worker("w1", {"device_seconds": 2.5})
    snap = driver.snapshot()
    assert snap["workers"]["w1"]["device_seconds"] == pytest.approx(2.5)
    assert driver.conservation()["device_seconds"]["total"] < 2.0


def test_usage_metrics_is_the_closed_vocabulary():
    m = TenantMeter()
    m.charge("t", None, {"device_seconds": 1.0, "bogus_metric": 9.0})
    assert "bogus_metric" not in m.snapshot()["tenants"]["t"]
    assert m.snapshot()["tenants"]["t"]["device_seconds"] == \
        pytest.approx(1.0)
    assert set(USAGE_METRICS) >= {"device_seconds", "hbm_byte_seconds",
                                  "shuffle_bytes", "spill_bytes",
                                  "scan_bytes", "compile_seconds",
                                  "queries"}


# ---------------------------------------------------------------------------
# live progress
# ---------------------------------------------------------------------------

class _FakeMetric:
    def __init__(self, rows):
        self.values = {"numOutputRows": float(rows)}


class _FakeCtx:
    def __init__(self, rows):
        self.metrics = {"ScanExec#0@p0": _FakeMetric(rows)}


class _FakeLc:
    def __init__(self, rows, fp, started):
        self.ctx = _FakeCtx(rows)
        self.plan_fingerprint = fp
        self._started_at = started


def test_live_progress_uses_row_medians_then_wall_fallback():
    import time as _t
    from spark_rapids_tpu.obs.history import HistoryIndex
    idx = HistoryIndex()
    for w in (2.0, 2.0, 2.0):
        idx.note_entry({"plan_fingerprint": "fp-p", "state": "FINISHED",
                        "wall_s": w, "rows_processed": 1000,
                        "metering": {"device_seconds": 0.5}})
    lc = _FakeLc(rows=500, fp="fp-p", started=_t.monotonic() - 1.0)
    out = live_progress(lc, idx)
    assert out["rows_processed"] == 500
    assert out["percent_complete"] == pytest.approx(50.0, abs=0.2)
    assert out["eta_s"] == pytest.approx(1.0, rel=0.2)
    assert out["median_wall_s"] == pytest.approx(2.0)
    # unknown fingerprint: rows still reported, no pct/eta invented
    out = live_progress(_FakeLc(500, "fp-never-seen",
                                _t.monotonic()), idx)
    assert out == {"rows_processed": 500}
    # history without row counts degrades to elapsed/median-wall
    idx2 = HistoryIndex()
    idx2.note_entry({"plan_fingerprint": "fp-w", "state": "FINISHED",
                     "wall_s": 4.0})
    lc = _FakeLc(rows=0, fp="fp-w", started=_t.monotonic() - 1.0)
    out = live_progress(lc, idx2)
    assert out["percent_complete"] == pytest.approx(25.0, abs=1.0)


# ---------------------------------------------------------------------------
# disabled path (in-process half; fresh-interpreter half in premerge)
# ---------------------------------------------------------------------------

def test_exec_ctx_profiler_is_none_when_disabled():
    from spark_rapids_tpu.exec.core import ExecCtx
    with ExecCtx(backend="device", conf=TpuConf({})) as ctx:
        assert ctx.profiler is None
        # the negative answer is cached so the hot path never re-reads
        # the conf
        assert ctx.cache.get("profiler") is None
        assert ctx.profiler is None
    with ExecCtx(backend="device", conf=_conf()) as ctx:
        p = ctx.profiler
        assert isinstance(p, QueryProfiler)
        assert ctx.profiler is p  # cached, not rebuilt per access


# ---------------------------------------------------------------------------
# HTTP views
# ---------------------------------------------------------------------------

@pytest.fixture()
def prof_session():
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession(dict(PROF_CONF))
    yield s
    s.shutdown()


def _get_json(url):
    import urllib.request
    with urllib.request.urlopen(url, timeout=5) as r:
        assert r.status == 200
        return json.loads(r.read())


def test_http_profile_and_tenants_views(prof_session):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.obs.http import ObsHttpServer
    schema = T.Schema([T.StructField("v", T.LongType(), True)])
    prof_session.from_pydict({"v": list(range(64))}, schema,
                             partitions=2).collect(tenant="acct")
    srv = ObsHttpServer(prof_session, 0)
    try:
        prof = _get_json(srv.address + "/profile")
        assert prof["enabled"] is True
        assert "hbm" in prof and "fingerprints" in prof
        ten = _get_json(srv.address + "/tenants")
        assert ten["enabled"] is True
        assert ten["tenants"]["acct"]["queries"] >= 1
        assert "conservation" in ten
        q = _get_json(srv.address + "/queries")
        assert q["count"] == 0
    finally:
        srv.close()


def test_http_views_answer_disabled_without_importing():
    from spark_rapids_tpu.obs.http import ObsHttpServer
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({})
    srv = ObsHttpServer(s, 0)
    try:
        assert _get_json(srv.address + "/profile") == {"enabled": False}
        assert _get_json(srv.address + "/tenants") == {"enabled": False}
    finally:
        srv.close()
        s.shutdown()


# ---------------------------------------------------------------------------
# history entries carry the cost-attribution fields
# ---------------------------------------------------------------------------

def test_history_entry_has_metering_rows_and_profile(tmp_path):
    import os
    import sys
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.obs.history import HISTORY_FILE
    from spark_rapids_tpu.session import TpuSession
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    from validate_obs import load_schema, validate
    s = TpuSession(dict(PROF_CONF, **{
        "spark.rapids.obs.history.dir": str(tmp_path)}))
    try:
        schema = T.Schema([T.StructField("v", T.LongType(), True)])
        s.from_pydict({"v": list(range(100))}, schema,
                      partitions=2).collect(tenant="etl")
    finally:
        s.shutdown()
    lines = [json.loads(ln) for ln in
             (tmp_path / HISTORY_FILE).read_text().splitlines() if ln]
    e = next(x for x in lines if x.get("state") == "FINISHED")
    assert validate(e, load_schema("history")) == []
    assert e["tenant"] == "etl"
    assert e["metering"]["device_seconds"] >= 0.0
    assert e["metering"]["queries"] == 1
    assert e["rows_processed"] >= 0
    assert e["profile"]["operators"]
    assert e["profile"]["device_seconds"] == pytest.approx(
        sum(o["device_s"] for o in e["profile"]["operators"].values()
            if o["parent"] is None), abs=1e-6)


# ---------------------------------------------------------------------------
# tools.history: top + show --profile (engine-free CLI)
# ---------------------------------------------------------------------------

def _write_history(tmp_path, entries):
    p = tmp_path / "query_history.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in entries))
    return str(tmp_path)


def _hist_entry(qid, fp, wall, tenant="etl", profile=None,
                metering=None):
    e = {"kind": "history", "version": 1, "query_id": qid,
         "tenant": tenant, "state": "FINISHED",
         "submitted_unix_s": 1_700_000_000.0, "wall_s": wall,
         "registry_delta": {"counters": {}, "histograms": {}},
         "plan_fingerprint": fp}
    if profile is not None:
        e["profile"] = profile
    if metering is not None:
        e["metering"] = metering
    return e


def test_tools_history_top_flags_regressions(tmp_path, capsys):
    from tools.history import main
    entries = (
        [_hist_entry(f"q-s{i}", "fp-steady", 1.0) for i in range(4)] +
        [_hist_entry(f"q-r{i}", "fp-regressed", 0.5) for i in range(2)] +
        [_hist_entry(f"q-r{i+2}", "fp-regressed", 2.0,
                     metering={"device_seconds": 0.25})
         for i in range(2)])
    rc = main(["--dir", _write_history(tmp_path, entries), "top"])
    out = capsys.readouterr().out
    assert rc == 0
    lines = out.splitlines()
    # sorted by median wall desc: the regressed fingerprint leads
    assert lines[1].startswith("fp-regressed"[:16])
    assert "REGRESSED(>2x)" in lines[1]
    assert "fp-steady"[:16] in lines[2] and "REGRESSED" not in lines[2]


def test_tools_history_show_profile_renders_member_rows(tmp_path,
                                                        capsys):
    from tools.history import main
    prof = {"device_seconds": 0.3, "hbm_byte_seconds": 12.5,
            "operators": {
                "FusedStageExec#1": {
                    "op": "FusedStageExec#1", "parent": None,
                    "device_s": 0.3, "wall_s": 0.35, "batches": 4,
                    "rows": 100},
                "FusedStageExec#1/ProjectExec": {
                    "op": "ProjectExec", "parent": "FusedStageExec#1",
                    "device_s": 0.15, "wall_s": 0.175, "batches": 4,
                    "rows": 100}}}
    d = _write_history(tmp_path, [
        _hist_entry("q-prof", "fp-x", 0.4, profile=prof,
                    metering={"device_seconds": 0.3})])
    rc = main(["--dir", d, "show", "q-prof", "--profile"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "FusedStageExec#1" in out
    assert "\n  ProjectExec" in out  # member indented under container
    assert "metered_device_s=0.3" in out
    # an entry without a stored profile explains itself, exit 1
    d = _write_history(tmp_path, [_hist_entry("q-bare", "fp-y", 0.1)])
    rc = main(["--dir", d, "show", "q-bare", "--profile"])
    assert rc == 1
    assert "no stored profile" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# registry: Prometheus label escaping + histogram merge under load
# ---------------------------------------------------------------------------

def test_prometheus_label_escaping_hostile_tenant_names():
    reg = MetricsRegistry()
    labeled = ['web-1', 'a.b.c', 'Ünïcôde™', 'q"uote', 'back\\slash']
    for i, t in enumerate(labeled):
        reg.inc(f"admission.tenant.{t}.admitted", i + 1)
    # a newline never crosses the dotted-name pattern ('.' stops at it)
    # so it degrades to a sanitized plain family, not a torn label
    reg.inc("admission.tenant.new\nline.admitted", 9)
    text = reg.to_prometheus()
    lines = [ln for ln in text.splitlines() if not ln.startswith("#")]
    assert len(lines) == len(labeled) + 1
    for ln in lines:
        # every sample stays one well-formed single-line series
        name, val = ln.rsplit(" ", 1)
        float(val)
        assert "\n" not in name
        if "{" not in name:
            continue
        assert name.startswith('srt_admission_tenant_admitted{tenant="')
        inner = name[name.index('{tenant="') + 9:-2]
        # quotes inside the label value are escaped
        assert not any(c == '"' and (i == 0 or inner[i - 1] != "\\")
                       for i, c in enumerate(inner))
    assert 'srt_admission_tenant_admitted{tenant="a.b.c"}' in text
    assert 'tenant="web-1"' in text
    assert 'tenant="Ünïcôde™"' in text
    assert 'tenant="q\\"uote"' in text
    assert 'tenant="back\\\\slash"' in text
    assert "srt_admission_tenant_new_line_admitted 9" in text


def test_prometheus_empty_label_value_falls_back_to_plain_family():
    reg = MetricsRegistry()
    # "admission.tenant..admitted" has an empty tenant: the labeled
    # pattern requires >=1 char, so it renders as a sanitized plain
    # family instead of an invalid empty-label series
    reg.inc("admission.tenant..admitted", 3)
    text = reg.to_prometheus()
    assert 'tenant=""' not in text
    assert "srt_admission_tenant__admitted 3" in text


def test_histogram_merge_exact_under_concurrent_observe():
    src = Histogram()
    acc = {"snap": None}
    stop = threading.Event()
    N_THREADS, N_OBS = 4, 2000

    def observe(seed):
        for i in range(N_OBS):
            src.observe(0.001 * ((seed * 31 + i) % 500 + 1))

    def scrape():
        while not stop.is_set():
            acc["snap"] = merge_histogram_snapshots(
                acc["snap"], None) if acc["snap"] else None
            snap = src.snapshot()
            # a torn snapshot would break the cumulative invariant
            assert sum(snap["counts"]) == snap["count"]

    workers = [threading.Thread(target=observe, args=(s,))
               for s in range(N_THREADS)]
    scraper = threading.Thread(target=scrape, daemon=True)
    scraper.start()
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    stop.set()
    scraper.join(timeout=5)
    final = src.snapshot()
    assert final["count"] == N_THREADS * N_OBS
    assert sum(final["counts"]) == final["count"]
    # merging two disjoint halves reproduces the whole exactly
    a, b = Histogram(), Histogram()
    for i in range(500):
        (a if i % 2 else b).observe(0.001 * (i % 100 + 1))
    merged = merge_histogram_snapshots(a.snapshot(), b.snapshot())
    whole = Histogram()
    for i in range(500):
        whole.observe(0.001 * (i % 100 + 1))
    assert merged["counts"] == whole.snapshot()["counts"]
    assert merged["count"] == 500
    assert merged["sum"] == pytest.approx(whole.snapshot()["sum"])


def test_histogram_merge_rebuckets_mismatched_bounds():
    a = Histogram(bounds=(0.001, 0.01, 0.1))
    b = Histogram(bounds=(0.005, 0.05))
    for v in (0.0005, 0.02, 5.0):
        a.observe(v)
        b.observe(v)
    m = merge_histogram_snapshots(a.snapshot(), b.snapshot())
    assert m["le"] == [0.001, 0.01, 0.1]  # a's bounds win
    assert m["count"] == 6
    assert sum(m["counts"]) == 6
    assert m["sum"] == pytest.approx(2 * (0.0005 + 0.02 + 5.0))

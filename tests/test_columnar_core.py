"""Core columnar round-trip + kernel tests (filter/sort/concat/groupby).

Reference test analogs: GpuCoalesceBatchesSuite, HashAggregatesSuite,
GpuSortExec coverage in tests/ (SURVEY §4.1).
"""
import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar import ColumnBatch
from spark_rapids_tpu import ops
from spark_rapids_tpu.ops.segmented import AggSpec, sorted_group_by
from spark_rapids_tpu.ops.sort import SortOrder


def _rb(**cols):
    return pa.RecordBatch.from_pydict(dict(cols))


def test_arrow_roundtrip_numeric():
    rb = _rb(a=pa.array([1, 2, None, 4], type=pa.int32()),
             b=pa.array([1.5, None, 3.5, -0.0], type=pa.float64()))
    batch = ColumnBatch.from_arrow(rb)
    assert batch.capacity == 8
    out = batch.to_arrow()
    assert out.column(0).to_pylist() == [1, 2, None, 4]
    assert out.column(1).to_pylist() == [1.5, None, 3.5, -0.0]


def test_arrow_roundtrip_strings():
    rb = _rb(s=pa.array(["hello", "", None, "worldly"]))
    batch = ColumnBatch.from_arrow(rb)
    out = batch.to_arrow()
    assert out.column(0).to_pylist() == ["hello", "", None, "worldly"]


def test_arrow_roundtrip_bool_date_ts():
    rb = _rb(f=pa.array([True, None, False], type=pa.bool_()),
             d=pa.array([0, 1000, None], type=pa.date32()),
             t=pa.array([0, 123456789, None], type=pa.timestamp("us")))
    out = ColumnBatch.from_arrow(rb).to_arrow()
    assert out.column(0).to_pylist() == [True, None, False]
    assert out.column(1).to_pylist()[1] == pa.scalar(1000, pa.date32()).as_py()
    assert out.column(2).to_pylist()[2] is None


def test_compact_filter():
    rb = _rb(a=pa.array([1, 2, 3, 4, 5], type=pa.int64()))
    batch = ColumnBatch.from_arrow(rb)
    keep = jnp.asarray([True, False, True, False, True, True, True, True])
    out = ops.compact(batch, keep)
    assert out.host_num_rows() == 3
    assert out.to_arrow().column(0).to_pylist() == [1, 3, 5]


def test_slice_limit():
    rb = _rb(a=pa.array(list(range(6)), type=pa.int32()))
    out = ops.slice_batch(ColumnBatch.from_arrow(rb), 4)
    assert out.to_arrow().column(0).to_pylist() == [0, 1, 2, 3]


def test_concat_batches():
    b1 = ColumnBatch.from_arrow(_rb(a=pa.array([1, None], type=pa.int32()),
                                    s=pa.array(["x", "yy"])))
    b2 = ColumnBatch.from_arrow(_rb(a=pa.array([3], type=pa.int32()),
                                    s=pa.array([None], type=pa.string())))
    out = ops.concat_batches([b1, b2])
    assert out.host_num_rows() == 3
    t = out.to_arrow()
    assert t.column(0).to_pylist() == [1, None, 3]
    assert t.column(1).to_pylist() == ["x", "yy", None]


@pytest.mark.parametrize("asc", [True, False])
def test_sort_ints_nulls(asc):
    rb = _rb(a=pa.array([5, None, 1, 3, None, 2], type=pa.int32()))
    batch = ColumnBatch.from_arrow(rb)
    out = ops.sort_batch(batch, [SortOrder(0, ascending=asc)])
    got = out.to_arrow().column(0).to_pylist()
    if asc:  # Spark: asc -> nulls first
        assert got == [None, None, 1, 2, 3, 5]
    else:    # desc -> nulls last
        assert got == [5, 3, 2, 1, None, None]


def test_sort_floats_nan_and_negzero():
    vals = [1.0, float("nan"), -1.0, 0.0, -0.0, float("inf"), float("-inf")]
    batch = ColumnBatch.from_arrow(_rb(a=pa.array(vals, type=pa.float64())))
    got = ops.sort_batch(batch, [SortOrder(0)]).to_arrow().column(0).to_pylist()
    assert got[0] == float("-inf")
    assert got[1] == -1.0
    assert got[2] == 0.0 and got[3] == 0.0
    assert got[4] == 1.0
    assert got[5] == float("inf")
    assert np.isnan(got[6])  # NaN largest, Spark semantics


def test_sort_strings():
    batch = ColumnBatch.from_arrow(_rb(s=pa.array(["pear", "apple", None, "ap", "banana"])))
    got = ops.sort_batch(batch, [SortOrder(0)]).to_arrow().column(0).to_pylist()
    assert got == [None, "ap", "apple", "banana", "pear"]


def test_sort_multi_key():
    batch = ColumnBatch.from_arrow(_rb(
        k=pa.array([2, 1, 2, 1], type=pa.int32()),
        v=pa.array([1.0, 5.0, 0.5, 4.0], type=pa.float64())))
    out = ops.sort_batch(batch, [SortOrder(0, True), SortOrder(1, False)])
    t = out.to_arrow()
    assert t.column(0).to_pylist() == [1, 1, 2, 2]
    assert t.column(1).to_pylist() == [5.0, 4.0, 1.0, 0.5]


def test_group_by_sum_count_min_max_avg():
    batch = ColumnBatch.from_arrow(_rb(
        k=pa.array([1, 2, 1, None, 2, 1], type=pa.int32()),
        v=pa.array([10, 20, None, 40, 5, 2], type=pa.int64())))
    out = sorted_group_by(batch, [0], [AggSpec("sum", 1), AggSpec("count", 1),
                                       AggSpec("min", 1), AggSpec("max", 1),
                                       AggSpec("avg", 1), AggSpec("count_star", 1)])
    t = out.to_arrow()
    rows = {t.column(0).to_pylist()[i]: tuple(t.column(j).to_pylist()[i] for j in range(1, 7))
            for i in range(out.host_num_rows())}
    assert rows[1] == (12, 2, 2, 10, 6.0, 3)
    assert rows[2] == (25, 2, 5, 20, 12.5, 2)
    assert rows[None] == (40, 1, 40, 40, 40.0, 1)


def test_group_by_all_null_values_sum_is_null():
    batch = ColumnBatch.from_arrow(_rb(
        k=pa.array([7, 7], type=pa.int32()),
        v=pa.array([None, None], type=pa.int64())))
    t = sorted_group_by(batch, [0], [AggSpec("sum", 1)]).to_arrow()
    assert t.column(1).to_pylist() == [None]


def test_grand_aggregate_empty_input():
    batch = ColumnBatch.from_arrow(
        pa.RecordBatch.from_pydict({"v": pa.array([], type=pa.int64())}))
    out = sorted_group_by(batch, [], [AggSpec("count", 0), AggSpec("sum", 0)])
    t = out.to_arrow()
    assert out.host_num_rows() == 1
    assert t.column(0).to_pylist() == [0]
    assert t.column(1).to_pylist() == [None]


def test_group_by_float_minmax_nan():
    batch = ColumnBatch.from_arrow(_rb(
        k=pa.array([1, 1, 1], type=pa.int32()),
        v=pa.array([1.0, float("nan"), -2.0], type=pa.float64())))
    t = sorted_group_by(batch, [0], [AggSpec("min", 1), AggSpec("max", 1)]).to_arrow()
    assert t.column(1).to_pylist() == [-2.0]
    assert np.isnan(t.column(2).to_pylist()[0])  # NaN is max in Spark

"""Elastic membership chaos matrix: live scale-up/down, graceful drain
with map-output migration, straggler speculation, flaky-worker
quarantine, and probe-before-death (spark_rapids_tpu/cluster/).

The reference engine rides Spark's dynamic allocation + speculative
execution + executor blacklisting; here the driver owns all three
directly: ``add_worker``/``remove_worker`` mutate the live pool with no
restart, a draining worker streams its map outputs to survivors over
the existing shuffle plane (tracker entries rewritten under an epoch
bump — a planned scale-down costs a copy, not a recompute), fragments
whose wall time exceeds ``speculation.multiplier`` x the running median
are re-dispatched with exactly-once commit via epoch-stale rejection,
and a worker past ``quarantine.maxFailures`` consecutive failures is
benched (outputs still servable) until probation re-admits it.

Every case asserts EXACT rows against a single-process oracle: the
elasticity machinery must never change an answer, only where the bytes
live.  Fast cases drive a pydict group-by; the q18 drain rides the
split-table TPC-H fixture (slow, like tests/test_cluster.py's chaos
paths).
"""
import os
import time

import numpy as np
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
])


def _mkdata(n, seed):
    rng = np.random.default_rng(seed)
    return {"k": [int(x) for x in rng.integers(0, 997, n)],
            "v": [int(x) for x in rng.integers(-1000, 1000, n)]}


def _oracle(data, partitions, rows_per_batch=512):
    s = TpuSession()
    try:
        df = s.from_pydict(data, SCHEMA, partitions=partitions,
                           rows_per_batch=rows_per_batch)
        return sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                      .collect())
    finally:
        s.shutdown()


@pytest.fixture(scope="module")
def dataset():
    """One shared dataset + single-process oracle for every pydict
    case: a group-by sum's rows do not depend on partition count, so
    each test picks its own fan-out against the same answer."""
    data = _mkdata(20000, seed=21)
    return data, _oracle(data, partitions=6)


def _drain_on_first_fetch(monkeypatch, drv, victim):
    """Retire ``victim`` synchronously at the reduce's FIRST map-output
    fetch: every map output is registered, the tracker is open, and no
    partition has been consumed — the canonical mid-query drain window,
    hit deterministically instead of racing a poll thread against the
    collect."""
    import spark_rapids_tpu.cluster.exec as cexec
    fired: dict = {}
    orig = cexec.ClusterMapOutputTracker.fetch_partition

    def hooked(self, shuffle_id, pid, lo=0, hi=None):
        if not fired:
            fired["ok"] = True
            fired.update(drv.remove_worker(victim, drain=True))
        return orig(self, shuffle_id, pid, lo, hi)

    monkeypatch.setattr(cexec.ClusterMapOutputTracker, "fetch_partition",
                        hooked)
    return fired


# ---------------------------------------------------------------------------
# case 1: live scale-up — the next query picks the new worker up
# ---------------------------------------------------------------------------

def test_scale_up_next_query_uses_new_worker(dataset):
    data, want = dataset
    s = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.cluster.maxWorkers": "3",
                    "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2"})
    try:
        df = s.from_pydict(data, SCHEMA, partitions=6, rows_per_batch=512)
        assert sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                      .collect()) == want
        drv = s._cluster()
        before = get_registry().snapshot()
        wid = drv.add_worker()
        assert wid == "w2"
        h = drv.worker_by_id(wid)
        assert h.alive and not h.draining
        # membership is a hard ceiling, not advisory
        with pytest.raises(RuntimeError, match="maxWorkers"):
            drv.add_worker()
        # the NEXT query's dispatch snapshot includes w2 with no restart
        assert sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                      .collect()) == want
        d = get_registry().delta(before)["counters"]
        assert d.get("cluster_workers_added", 0) == 1, d
        # w2 heartbeats its own registry; wait for proof it ran fragments
        deadline = time.monotonic() + 10.0
        ran = 0
        while time.monotonic() < deadline:
            # object sources (the worker's metrics dict) export as gauges
            ran = ((h.metrics or {}).get("gauges") or {}).get(
                "cluster.worker.fragments_run", 0)
            if ran >= 1:
                break
            time.sleep(0.1)
        assert ran >= 1, "scaled-up worker never ran a fragment"
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 2 (fast twin of the q18 drain): mid-query retirement migrates,
# never recomputes
# ---------------------------------------------------------------------------

def test_drain_mid_query_migrates_without_recompute(dataset, monkeypatch):
    data, want = dataset
    s = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2"})
    try:
        df = s.from_pydict(data, SCHEMA, partitions=8, rows_per_batch=512)
        drv = s._cluster()
        fired = _drain_on_first_fetch(monkeypatch, drv, "w1")
        before = get_registry().snapshot()
        got = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                     .collect())
        assert fired.get("ok"), "drain never triggered mid-query"
        assert got == want
        d = get_registry().delta(before)["counters"]
        assert d.get("map_outputs_migrated", 0) > 0, d
        assert d.get("stage_recomputes", 0) == 0, d
        assert d.get("cluster_workers_drained", 0) == 1, d
        h = drv.worker_by_id("w1")
        assert h.retired and not h.alive
        assert h.proc.poll() is not None, "retired worker still running"
        # retirement shows as planned in health, not as a loss
        assert h.state == "retired" and h.lost_reason == "drained"
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 3: drain that LOSES a slot falls back to lineage — exactly once
# ---------------------------------------------------------------------------

def test_drain_with_migrate_drop_recomputes_exactly_once(dataset,
                                                         monkeypatch):
    data, want = dataset
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2",
        "spark.rapids.test.faults": "cluster.migrate.drop:drop,times=1",
        "spark.rapids.shuffle.tcp.maxRetries": 1,
        "spark.rapids.shuffle.tcp.retryWaitSeconds": 0.1,
    })
    try:
        df = s.from_pydict(data, SCHEMA, partitions=8, rows_per_batch=512)
        drv = s._cluster()
        fired = _drain_on_first_fetch(monkeypatch, drv, "w1")
        before = get_registry().snapshot()
        got = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                     .collect())
        assert fired.get("ok"), "drain never triggered mid-query"
        assert got == want
        # the drop withholds ONE whole map output (all its slots stay at
        # the old epoch on the retiring worker); lineage recomputes that
        # map task exactly once and everything else rides the migration
        assert fired["dropped"] > 0 and fired["migrated"] > 0, fired
        d = get_registry().delta(before)["counters"]
        assert d.get("faults.injected.cluster.migrate.drop", 0) == 1, d
        assert d.get("stage_recomputes", 0) == 1, d
        assert d.get("map_outputs_migrated", 0) == fired["migrated"], d
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 4: straggler speculation — duplicate wins, exactly-once commit
# ---------------------------------------------------------------------------

def test_straggler_speculation_exact_rows(dataset):
    data, want = dataset
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.speculation.enabled": "true",
        "spark.rapids.cluster.speculation.multiplier": "2.0",
        "spark.rapids.cluster.speculation.minRuntimeSeconds": "0.2",
        # the fault registry is per query: times=1 holds ONE worker's
        # fragment for 3s in each query's dispatch round
        "spark.rapids.test.faults":
            "cluster.worker.slow:slow,seconds=2.0,worker=w1,times=1",
    })
    try:
        df = s.from_pydict(data, SCHEMA, partitions=6, rows_per_batch=512)
        # warm-up compiles both stages so the healthy worker's wall time
        # seeds a tight speculation median
        assert sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                      .collect()) == want
        before = get_registry().snapshot()
        t0 = time.monotonic()
        got = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                     .collect())
        wall = time.monotonic() - t0
        assert got == want
        d = get_registry().delta(before)["counters"]
        assert d.get("faults.injected.cluster.worker.slow", 0) == 1, d
        assert d.get("speculative_launched", 0) >= 1, d
        # the duplicate — not lineage recovery — absorbed the straggler
        assert d.get("stage_recomputes", 0) == 0, d
        assert wall < 2.0, f"speculation did not beat the 2s straggler " \
                           f"(wall={wall:.2f}s)"
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 5: flaky worker quarantined, outputs stay servable, probation
# re-admits
# ---------------------------------------------------------------------------

def test_flaky_worker_quarantine_and_readmission(dataset):
    data, want = dataset
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.quarantine.maxFailures": "2",
        "spark.rapids.cluster.quarantine.probationSeconds": "4.0",
        "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2",
        "spark.rapids.test.faults":
            "cluster.worker.flaky:flaky,worker=w1,times=2",
    })
    try:
        df = s.from_pydict(data, SCHEMA, partitions=6, rows_per_batch=512)
        drv = s._cluster()
        before = get_registry().snapshot()
        got = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                     .collect())
        assert got == want
        d = get_registry().delta(before)["counters"]
        assert d.get("faults.injected.cluster.worker.flaky", 0) == 2, d
        assert d.get("cluster_workers_quarantined", 0) == 1, d
        h = drv.worker_by_id("w1")
        assert h.alive and h.quarantined_until is not None
        assert h.state == "quarantined"
        assert "w1" not in [w.worker_id for w in drv.schedulable_workers()]
        # a quarantined worker gets no NEW fragments but its shuffle
        # server still answers: a fresh query must stay exact while only
        # w0 is schedulable
        got2 = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                      .collect())
        assert got2 == want
        # probation elapses -> the monitor re-admits and resets failures
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if drv.worker_by_id("w1").quarantined_until is None:
                break
            time.sleep(0.1)
        h = drv.worker_by_id("w1")
        assert h.quarantined_until is None and h.alive and h.failures == 0
        d = get_registry().delta(before)["counters"]
        assert d.get("cluster_workers_readmitted", 0) == 1, d
        assert "w1" in [w.worker_id for w in drv.schedulable_workers()]
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 6: heartbeat stall with a live RPC plane — the probe saves the
# worker from a false death verdict
# ---------------------------------------------------------------------------

def test_heartbeat_stall_probe_saves_worker(dataset):
    data, want = dataset
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2",
        "spark.rapids.cluster.heartbeat.timeoutSeconds": "1.0",
        # the driver DROPS w1's heartbeats; the worker itself stays live
        "spark.rapids.test.faults": "cluster.worker.hang:hang,worker=w1",
    })
    try:
        drv = s._cluster()
        before = get_registry().snapshot()
        deadline = time.monotonic() + 15.0
        saves = 0
        while time.monotonic() < deadline:
            saves = get_registry().delta(before)["counters"].get(
                "cluster_death_probe_saves", 0)
            if saves >= 1:
                break
            time.sleep(0.1)
        d = get_registry().delta(before)["counters"]
        assert saves >= 1, f"probe never fired: {d}"
        assert d.get("cluster_death_probes", 0) >= 1, d
        h = drv.worker_by_id("w1")
        assert h.alive and h.lost_reason is None, \
            "probe-reachable worker was declared dead"
        # the saved worker still computes: exact rows, zero recovery
        df = s.from_pydict(data, SCHEMA, partitions=6, rows_per_batch=512)
        got = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"))
                     .collect())
        assert got == want
        d = get_registry().delta(before)["counters"]
        assert d.get("stage_recomputes", 0) == 0, d
        assert d.get("cluster_workers_lost", 0) == 0, d
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# min/max membership floors
# ---------------------------------------------------------------------------

def test_membership_floor_blocks_scale_down():
    s = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.cluster.minWorkers": "2"})
    try:
        drv = s._cluster()
        with pytest.raises(RuntimeError, match="minWorkers"):
            drv.remove_worker("w1", drain=True)
        with pytest.raises(KeyError):
            drv.remove_worker("w99")
        assert len([h for h in drv.workers() if h.alive]) == 2
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# the q18 drain (slow): mid-query retirement under a real TPC-H plan
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_elastic") / "sf001")
    generate_tpch(d, sf=0.01)
    _split_tables(d, ("lineitem", "orders", "customer"), parts=4)
    return d


def _split_tables(data_dir: str, tables, parts: int) -> None:
    import pyarrow.parquet as pq
    for table in tables:
        path = os.path.join(data_dir, table, "part-0.parquet")
        t = pq.read_table(path)
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(data_dir, table,
                                        f"part-{i}.parquet"))


@pytest.mark.slow
def test_tpch_q18_drain_mid_query_exact(tpch_dir, monkeypatch):
    s0 = TpuSession()
    want = sorted(build_tpch_query("q18", s0, tpch_dir).collect())
    s0.shutdown()
    s = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2"})
    try:
        df = build_tpch_query("q18", s, tpch_dir)
        drv = s._cluster()
        fired = _drain_on_first_fetch(monkeypatch, drv, "w1")
        before = get_registry().snapshot()
        got = sorted(df.collect())
        assert fired.get("ok"), "drain never triggered mid-q18"
        assert got == want
        d = get_registry().delta(before)["counters"]
        assert d.get("map_outputs_migrated", 0) > 0, d
        assert d.get("stage_recomputes", 0) == 0, d
        h = drv.worker_by_id("w1")
        assert h.retired and h.proc.poll() is not None
    finally:
        s.shutdown(drain=True)

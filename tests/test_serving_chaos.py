"""Serving-tier chaos: multi-tenant streams under memory storms and
cache corruption.

Three tenants with 3:1:1 weights stream repeated TPC-H q3/q13/q18
through ONE session — one admission controller, one result cache, one
memory governor — while a deterministic HBM-exhaustion storm forces
split-and-retry and the ``cache.result.corrupt`` fault poisons cache
hits.  Required outcomes (ISSUE 12 satellite): every result exact
against the host oracle, cache hits > 0, ZERO stale hits after an
input-file mtime bump, weighted admission shares within tolerance
while all tenants are backlogged, no tenant starved, and zero leaked
reservations or consumer pins after ``shutdown(drain=True)``.
"""
import gc
import os
import threading
import time

import pytest

from spark_rapids_tpu.bench.runner import _rows_match
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.exec.result_cache import get_result_cache
from spark_rapids_tpu.memory.governor import get_governor
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

_QUERIES = ["q3", "q13", "q18"]
_TENANTS = {"etl": 3, "bi": 1, "adhoc": 1}
_ROUNDS = 2
_WALL_LIMIT_S = 420.0

_CHAOS_CONF = {
    "spark.rapids.test.faults":
        "memory.oom.until_rows:oom,until_rows=8192;"
        "cache.result.corrupt:corrupt,times=2",
    "spark.rapids.memory.host.spillStorageSize": 64 << 20,
    "spark.rapids.sql.admission.maxConcurrentQueries": 2,
    "spark.rapids.sql.admission.tenantWeights": "etl:3,bi:1,adhoc:1",
}


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_serving") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


def _oracle(df):
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = df._overridden(quiet=True)
    return collect_host(meta.exec_node, df._s.conf)


def test_three_tenant_streams_exact_under_storm(data_dir):
    session = TpuSession(dict(_CHAOS_CONF))
    gov = get_governor()
    cache = get_result_cache()
    before = get_registry().snapshot()["counters"]
    oracles = {q: _oracle(build_tpch_query(q, session, data_dir))
               for q in _QUERIES}

    finished: dict = {t: 0 for t in _TENANTS}
    mismatches: list = []
    errors: list = []

    def stream(tenant: str, k: int):
        # distinct permutation per tenant (throughput-test shape)
        order = [_QUERIES[(i + k) % len(_QUERIES)]
                 for i in range(len(_QUERIES))]
        for _round in range(_ROUNDS):
            for q in order:
                try:
                    # fresh plan per run: AQE mutates scan exec nodes
                    rows = build_tpch_query(q, session, data_dir) \
                        .collect(tenant=tenant)
                except Exception as ex:  # noqa: BLE001 - recorded for asserts
                    errors.append((tenant, q, repr(ex)))
                    return
                if not _rows_match(rows, oracles[q]):
                    mismatches.append((tenant, q))
                finished[tenant] += 1

    threads = [threading.Thread(target=stream, args=(t, k), daemon=True)
               for k, t in enumerate(_TENANTS)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(_WALL_LIMIT_S - (time.monotonic() - t0))
    wall = time.monotonic() - t0
    assert not [t for t in threads if t.is_alive()], \
        f"serving livelock: streams still running after {wall:.0f}s"
    assert not errors, errors
    assert not mismatches, mismatches

    moved = get_registry().delta({"counters": before})["counters"]
    # the storm and the corruption both actually fired, and the cache
    # still carried real traffic: repeats/coalesces hit, corruption was
    # a verified drop-and-recompute, never a wrong row (asserted above)
    assert moved.get("faults.injected.memory.oom.until_rows", 0) > 0
    assert moved.get("result_cache_hits", 0) > 0
    assert moved.get("result_cache_corrupt", 0) >= 1
    # no starvation: every tenant finished its full stream
    for tenant in _TENANTS:
        assert finished[tenant] == _ROUNDS * len(_QUERIES), finished

    # ---- zero stale hits after an input mtime bump -------------------
    now = time.time_ns()
    for root, _dirs, files in os.walk(data_dir):
        for f in files:
            os.utime(os.path.join(root, f), ns=(now, now))
    before_bump = get_registry().snapshot()["counters"]
    for q in _QUERIES:
        rows = build_tpch_query(q, session, data_dir).collect(tenant="etl")
        assert _rows_match(rows, oracles[q]), f"{q} stale/inexact"
    bump_moved = get_registry().delta(
        {"counters": before_bump})["counters"]
    assert bump_moved.get("result_cache_hits", 0) == 0, \
        "stale hit served after input mtime bump"
    assert bump_moved.get("queries_executed", 0) == len(_QUERIES)

    # ---- weighted shares: deterministic admission order --------------
    # saturate the only remaining capacity and backlog all three
    # tenants, then let the cascade drain: stride scheduling must give
    # etl ~3/5 of the contended window
    ac = session._admission_controller()
    ac.admit("blocker")
    ac.admit("blocker2")      # maxConcurrentQueries=2
    backlog = [("etl", 8), ("bi", 4), ("adhoc", 4)]
    waiters = []
    n_queued = 0
    for tenant, count in backlog:
        for i in range(count):
            def wait_in(t=tenant, n=i):
                ac.admit(f"{t}-{n}", tenant=t)
                ac.release(tenant=t)

            th = threading.Thread(target=wait_in)
            th.start()
            waiters.append(th)
            n_queued += 1
            deadline = time.monotonic() + 5.0
            while ac.queued < n_queued and time.monotonic() < deadline:
                time.sleep(0.002)
    log_start = len(ac.admission_log)
    ac.release()
    ac.release()
    for th in waiters:
        th.join(timeout=10.0)
        assert not th.is_alive()
    window = [t for t, _q in list(ac.admission_log)[log_start:]][:10]
    # expected 6:2:2 in the first 10 under weights 3:1:1 — allow ±1
    assert 5 <= window.count("etl") <= 7, window
    assert window.count("bi") >= 1 and window.count("adhoc") >= 1, window

    # ---- zero leaks after drain --------------------------------------
    session.shutdown(drain=True)
    gc.collect()
    assert gov.reserved_bytes() == 0, "grant reservation leaked"
    with cache._lock:
        pinned = [e.key for e in cache._entries.values()
                  if e.consumers > 0]
    assert not pinned, f"consumer pins leaked: {pinned}"

"""Query lifecycle control plane suite: deadlines, cooperative
cancellation, admission control, graceful shutdown.

The invariant under test is the one the reference gets from Spark's
task-kill machinery (TaskContext.isInterrupted + GpuSemaphore releasing
the device for killed tasks): a cancelled or deadline-exceeded query
unwinds through the SAME finally blocks as a successful one, so nothing
leaks — the DeviceSemaphore returns to full capacity, spilled files are
unlinked, parked spillable batches are closed, and the terminal
QueryCancelled / QueryDeadlineExceeded is never swallowed by the OOM
split-and-retry scope, the shuffle fetch ladder, or stage recovery.

The integration half cancels TPC-H q3 mid-flight under the PR-1/PR-3
chaos storm (peer death + spilled-output corruption + tiny budgets), so
cancellation lands while retries, recovery and spill I/O are all in
motion — the worst case for a leak, not the best.
"""
import os
import socket
import threading
import time

import pytest

from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.lifecycle import (ADMITTED, CANCELLED,
                                             DEADLINE_EXCEEDED, FINISHED,
                                             RUNNING, AdmissionController,
                                             QueryCancelled,
                                             QueryDeadlineExceeded,
                                             QueryLifecycle, QueryRejected)
from spark_rapids_tpu.obs.registry import get_registry


def _counter_delta(before: dict, name: str) -> float:
    return get_registry().delta(before)["counters"].get(name, 0)


# ---------------------------------------------------------------------------
# QueryLifecycle state machine
# ---------------------------------------------------------------------------

def test_state_machine_happy_path():
    lc = QueryLifecycle("q1")
    assert lc.state == ADMITTED
    lc.start()
    assert lc.state == RUNNING
    lc.check()  # no deadline, not cancelled: no-op
    assert lc.finish()
    assert lc.state == FINISHED
    # terminal is sticky: neither fail nor cancel moves it
    assert not lc.fail()
    assert not lc.cancel()
    assert lc.state == FINISHED


def test_cancel_idempotent_counts_once():
    before = get_registry().snapshot()
    lc = QueryLifecycle("q2")
    lc.start()
    assert lc.cancel("test")
    assert not lc.cancel("again")
    assert not lc.cancel("and again")
    assert lc.state == CANCELLED
    assert lc.cancel_event.is_set()
    assert _counter_delta(before, "queries_cancelled") == 1
    with pytest.raises(QueryCancelled, match="test"):
        lc.check()


def test_deadline_expires_at_check():
    before = get_registry().snapshot()
    lc = QueryLifecycle("q3", timeout=0.02)
    lc.start()
    time.sleep(0.05)
    with pytest.raises(QueryDeadlineExceeded):
        lc.check()
    assert lc.state == DEADLINE_EXCEEDED
    assert lc.cancel_event.is_set()
    # a cancel after expiry is a no-op and must not double-count
    assert not lc.cancel()
    assert _counter_delta(before, "queries_deadline_exceeded") == 1
    assert _counter_delta(before, "queries_cancelled") == 0


def test_deadline_clock_starts_at_start_not_admission():
    lc = QueryLifecycle("q4", timeout=5.0)
    assert lc.remaining() is None      # not started: no deadline yet
    lc.start()
    rem = lc.remaining()
    assert rem is not None and 4.0 < rem <= 5.0


def test_from_conf_tighter_of_conf_and_call():
    conf = TpuConf({"spark.rapids.sql.queryTimeout": 5.0})
    assert QueryLifecycle.from_conf("a", conf).timeout == 5.0
    assert QueryLifecycle.from_conf("b", conf, timeout=1.0).timeout == 1.0
    assert QueryLifecycle.from_conf("c", conf, timeout=9.0).timeout == 5.0
    assert QueryLifecycle.from_conf("d", TpuConf({})).timeout is None


def test_wait_interrupted_by_cancel():
    lc = QueryLifecycle("q5")
    lc.start()
    t = threading.Timer(0.15, lc.cancel)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(QueryCancelled):
        lc.wait(30.0)
    assert time.monotonic() - t0 < 5.0   # woke at the cancel, not 30s
    t.join()


def test_wait_capped_by_deadline():
    lc = QueryLifecycle("q6", timeout=0.1)
    lc.start()
    t0 = time.monotonic()
    with pytest.raises(QueryDeadlineExceeded):
        lc.wait(30.0)
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# terminal taxonomy vs the retry ladders
# ---------------------------------------------------------------------------

def test_is_oom_refuses_terminal_errors():
    from spark_rapids_tpu.memory.retry import is_oom
    # message LOOKS like an OOM; terminal=True must win
    e = QueryCancelled("q", "RESOURCE_EXHAUSTED: not really")
    assert not is_oom(e)
    assert not is_oom(QueryDeadlineExceeded("q", 1.0))
    assert is_oom(RuntimeError("RESOURCE_EXHAUSTED: real"))


def test_with_retry_does_not_swallow_cancel():
    from spark_rapids_tpu.memory.retry import with_retry

    calls = []

    def fn(_b):
        calls.append(1)
        raise QueryCancelled("q", "RESOURCE_EXHAUSTED: disguised")

    class _Cat:
        pass

    with pytest.raises(QueryCancelled):
        with_retry(fn, _Cat(), object())
    assert len(calls) == 1   # no second attempt, no split


def test_dispatch_entry_is_a_cancellation_point():
    from spark_rapids_tpu.exec.core import ExecCtx
    with ExecCtx(backend="device", conf=TpuConf({})) as ctx:
        ctx.lifecycle.cancel("test")
        with pytest.raises(QueryCancelled):
            ctx.check_cancel()
        with pytest.raises(QueryCancelled):
            ctx.dispatch(lambda: 1)


def test_udf_slot_acquire_is_a_cancellation_point():
    from spark_rapids_tpu.exec.python_exec import _udf_slot
    sem = threading.BoundedSemaphore(1)
    lc = QueryLifecycle("qudf")
    lc.start()
    assert sem.acquire()   # saturate: the slot is unavailable
    errs = []

    def worker():
        try:
            with _udf_slot(sem, lc):
                pass
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            errs.append(e)

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.15)       # worker is polling for the slot
    lc.cancel("test")
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert errs and isinstance(errs[0], QueryCancelled)
    sem.release()
    # the cancelled waiter must NOT have consumed the permit
    assert sem.acquire(blocking=False)
    sem.release()


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_unbounded_by_default():
    ac = AdmissionController(max_concurrent=0)
    for i in range(32):
        ac.admit(f"q{i}")
    assert ac.active == 32


def test_admission_queue_overflow_rejected():
    before = get_registry().snapshot()
    ac = AdmissionController(max_concurrent=1, max_queued=1,
                             queue_timeout=30.0)
    ac.admit("holder")

    queued = threading.Thread(target=ac.admit, args=("waiter",))
    queued.start()
    deadline = time.monotonic() + 5.0
    while ac.queued < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert ac.queued == 1

    with pytest.raises(QueryRejected, match="queue full"):
        ac.admit("overflow")
    assert _counter_delta(before, "queries_rejected") == 1

    ac.release()           # holder done -> waiter admitted
    queued.join(timeout=5.0)
    assert not queued.is_alive()
    assert ac.active == 1 and ac.queued == 0
    assert _counter_delta(before, "queries_admitted") == 2


def test_admission_is_fifo():
    ac = AdmissionController(max_concurrent=1, max_queued=8,
                             queue_timeout=30.0)
    ac.admit("holder")
    order: list = []

    def wait_in(name):
        ac.admit(name)
        order.append(name)

    threads = []
    for i in range(3):
        t = threading.Thread(target=wait_in, args=(f"w{i}",))
        t.start()
        threads.append(t)
        deadline = time.monotonic() + 5.0
        while ac.queued < i + 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert ac.queued == i + 1   # arrival order is pinned

    for i in range(3):
        ac.release()
        deadline = time.monotonic() + 5.0
        while len(order) < i + 1 and time.monotonic() < deadline:
            time.sleep(0.005)
    for t in threads:
        t.join(timeout=5.0)
    assert order == ["w0", "w1", "w2"]


def test_admission_queue_timeout_rejects():
    ac = AdmissionController(max_concurrent=1, max_queued=4,
                             queue_timeout=0.15)
    ac.admit("holder")
    t0 = time.monotonic()
    with pytest.raises(QueryRejected, match="queueTimeoutSeconds"):
        ac.admit("late")
    assert 0.1 <= time.monotonic() - t0 < 5.0
    assert ac.queued == 0   # the timed-out token was removed


def test_admission_shutdown_rejects_new_and_queued():
    ac = AdmissionController(max_concurrent=1, max_queued=4,
                             queue_timeout=30.0)
    ac.admit("holder")
    errs = []

    def waiter():
        try:
            ac.admit("queued")
        except QueryRejected as e:
            errs.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 5.0
    while ac.queued < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    ac.begin_shutdown()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert errs and "shutting down" in str(errs[0])
    with pytest.raises(QueryRejected, match="shutting down"):
        ac.admit("new")
    # already-admitted queries are unaffected
    assert ac.active == 1


# ---------------------------------------------------------------------------
# weighted-fair multi-tenant admission + cancel-while-queued
# ---------------------------------------------------------------------------

def test_parse_tenant_map():
    from spark_rapids_tpu.exec.lifecycle import parse_tenant_map
    assert parse_tenant_map("") == {}
    assert parse_tenant_map("etl:3,dash:1") == {"etl": 3.0, "dash": 1.0}
    assert parse_tenant_map("a:2", conv=int) == {"a": 2}
    with pytest.raises(ValueError):
        parse_tenant_map("no-colon")
    with pytest.raises(ValueError):
        parse_tenant_map("a:notanumber")


def _queue_waiters(ac, specs):
    """Start one admit-then-release thread per (tenant, name), pinning
    arrival order by waiting for the queue to grow between starts."""
    threads = []
    for i, (tenant, name) in enumerate(specs):
        def wait_in(t=tenant, n=name):
            ac.admit(n, tenant=t)
            ac.release(tenant=t)

        th = threading.Thread(target=wait_in)
        th.start()
        threads.append(th)
        deadline = time.monotonic() + 5.0
        while ac.queued < i + 1 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert ac.queued == i + 1
    return threads


def test_weighted_fair_admission_order():
    from spark_rapids_tpu.exec.lifecycle import AdmissionController
    ac = AdmissionController(max_concurrent=1, max_queued=16,
                             queue_timeout=30.0,
                             tenant_weights={"etl": 3.0, "dash": 1.0})
    ac.admit("holder")
    specs = [("etl", f"e{i}") for i in range(6)] + \
            [("dash", f"d{i}") for i in range(2)]
    threads = _queue_waiters(ac, specs)
    ac.release()           # holder done -> the cascade drains the queue
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive()
    log = [tenant for tenant, _q in ac.admission_log
           if tenant != "default"]
    assert len(log) == 8
    # stride scheduling: a weight-3 tenant gets 3 of every 4 slots
    # while both are backlogged — assert the share over the window
    # where dash was still queued, not one exact interleaving
    assert log.count("etl") == 6 and log.count("dash") == 2
    last_dash = max(i for i, t in enumerate(log) if t == "dash")
    window = log[:last_dash + 1]
    assert window.count("etl") >= 2 * window.count("dash"), log
    # and no tenant was starved: the first 4 admissions include dash
    assert "dash" in log[:4], log


def test_single_tenant_stays_fifo_with_weights_configured():
    from spark_rapids_tpu.exec.lifecycle import AdmissionController
    ac = AdmissionController(max_concurrent=1, max_queued=8,
                             queue_timeout=30.0,
                             tenant_weights={"etl": 3.0})
    ac.admit("holder")
    threads = _queue_waiters(ac, [("default", f"w{i}") for i in range(3)])
    ac.release()
    for t in threads:
        t.join(timeout=10.0)
    assert [q for t, q in ac.admission_log if t == "default"] == \
        ["holder", "w0", "w1", "w2"]


def test_tenant_cap_does_not_block_neighbors():
    from spark_rapids_tpu.exec.lifecycle import AdmissionController
    ac = AdmissionController(max_concurrent=4, max_queued=8,
                             queue_timeout=30.0,
                             tenant_max_concurrent={"capped": 1})
    ac.admit("c1", tenant="capped")      # capped tenant at its cap
    done = []

    def capped_waiter():
        ac.admit("c2", tenant="capped")  # must queue behind the cap
        done.append("c2")

    t = threading.Thread(target=capped_waiter)
    t.start()
    deadline = time.monotonic() + 5.0
    while ac.queued < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    assert ac.queued == 1
    # global capacity exists: another tenant must sail past the
    # capped tenant's backlog
    ac.admit("o1", tenant="other")
    assert ac.active == 2 and not done
    ac.release(tenant="capped")          # c1 done -> c2 admits
    t.join(timeout=5.0)
    assert done == ["c2"]


def test_deadline_ordering_admits_tightest_first():
    from spark_rapids_tpu.exec.lifecycle import AdmissionController
    ac = AdmissionController(max_concurrent=1, max_queued=8,
                             queue_timeout=30.0, deadline_ordering=True)
    ac.admit("holder")
    lc_loose = QueryLifecycle("loose", timeout=60.0)
    lc_tight = QueryLifecycle("tight", timeout=0.8)
    order: list = []

    def wait_in(name, lc):
        ac.admit(name, lifecycle=lc)
        order.append(name)
        ac.release()

    threads = []
    for name, lc in (("loose", lc_loose), ("tight", lc_tight)):
        t = threading.Thread(target=wait_in, args=(name, lc))
        t.start()
        threads.append(t)
        deadline = time.monotonic() + 5.0
        while ac.queued < len(threads) and time.monotonic() < deadline:
            time.sleep(0.002)
    ac.release()
    for t in threads:
        t.join(timeout=10.0)
    # EDF within the tenant: the tight deadline overtakes the earlier
    # arrival instead of missing its deadline behind it
    assert order == ["tight", "loose"]


def test_cancel_while_queued_releases_slot_counts_once():
    from spark_rapids_tpu.exec.lifecycle import AdmissionController
    before = get_registry().snapshot()
    ac = AdmissionController(max_concurrent=1, max_queued=4,
                             queue_timeout=30.0)
    ac.admit("holder")
    lc = QueryLifecycle("queued")
    errs: list = []

    def waiter():
        try:
            ac.admit("queued", lifecycle=lc)
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            errs.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 5.0
    while ac.queued < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    assert ac.queued == 1
    assert lc.cancel("user abort")
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert errs and isinstance(errs[0], QueryCancelled)
    # the queue token was released and the accounting is exact:
    # one cancellation, ZERO rejections (idempotent-cancel extended
    # to the queued state)
    assert ac.queued == 0
    assert not lc.cancel("again")
    assert _counter_delta(before, "queries_cancelled") == 1
    assert _counter_delta(before, "queries_rejected") == 0
    # the slot still works: the next arrival flows normally
    ac.release()
    ac.admit("next")
    assert ac.active == 1


def test_session_cancel_reaches_queued_query(data_dir):
    """A collect still waiting in the admission queue is visible in
    active_queries() and cancellable — the session registers the
    lifecycle BEFORE admission."""
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.session import TpuSession
    session = TpuSession({
        "spark.rapids.sql.admission.maxConcurrentQueries": 1,
        "spark.rapids.sql.resultCache.enabled": "false",
    })
    ac = session._admission_controller()
    ac.admit("blocker")            # saturate the only slot
    before = get_registry().snapshot()
    df = build_tpch_query("q6", session, data_dir)
    outcome: list = []

    def run():
        try:
            outcome.append(("ok", df.collect()))
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            outcome.append(("err", e))

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 10.0
    while ac.queued < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    assert ac.queued == 1
    qids = session.active_queries()
    assert len(qids) == 1          # queued, not yet admitted — but live
    assert session.cancel(qids[0])
    t.join(timeout=10.0)
    assert not t.is_alive()
    kind, val = outcome[0]
    assert kind == "err" and isinstance(val, QueryCancelled), outcome
    assert ac.queued == 0
    assert _counter_delta(before, "queries_cancelled") == 1
    assert _counter_delta(before, "queries_rejected") == 0
    assert session.active_queries() == []
    ac.release()                   # the manual blocker


def test_pressure_shed_hits_over_share_tenant_only():
    from spark_rapids_tpu.exec.lifecycle import (AdmissionController,
                                                 QueryRejected)
    before = get_registry().snapshot()
    ac = AdmissionController(max_concurrent=0)
    for i in range(3):
        ac.admit(f"h{i}", tenant="hog")
    ac.admit("q0", tenant="quiet")
    ac.pressure_hook = lambda tenant: "memory pressure: test"
    # hog holds 3 of 4 slots at equal weight: over its share -> shed
    with pytest.raises(QueryRejected, match="memory pressure"):
        ac.admit("h3", tenant="hog")
    # quiet is under its share: spared, admitted, counted
    ac.admit("q1", tenant="quiet")
    d = get_registry().delta(before)["counters"]
    assert d.get("admission_pressure_spared") == 1
    assert d.get("admission.tenant.hog.rejected") == 1
    assert d.get("admission.tenant.quiet.rejected", 0) == 0
    # single-tenant degenerate case: the only tenant is always at its
    # share, so pressure sheds it — identical to the pre-tenant gate
    ac2 = AdmissionController(max_concurrent=0)
    ac2.admit("a", tenant="default")
    ac2.pressure_hook = lambda tenant: "memory pressure: test"
    with pytest.raises(QueryRejected):
        ac2.admit("b", tenant="default")


def test_admission_tenant_storm_fault_sheds_only_that_tenant():
    from spark_rapids_tpu.exec.lifecycle import (AdmissionController,
                                                 QueryRejected)
    from spark_rapids_tpu.faults import FaultRegistry
    before = get_registry().snapshot()
    ac = AdmissionController(max_concurrent=0)
    ac.faults = FaultRegistry(
        "admission.tenant.storm:storm,tenant=noisy,times=2")
    with pytest.raises(QueryRejected, match="admission storm"):
        ac.admit("n1", tenant="noisy")
    ac.admit("c1", tenant="calm")          # unaffected tenant flows
    with pytest.raises(QueryRejected):
        ac.admit("n2", tenant="noisy")
    ac.admit("n3", tenant="noisy")         # times=2 exhausted
    d = get_registry().delta(before)["counters"]
    assert d.get("admission.tenant.noisy.rejected") == 2
    assert d.get("admission.tenant.calm.admitted") == 1
    assert d.get("faults.injected.admission.tenant.storm") == 2


# ---------------------------------------------------------------------------
# early consumer exit stops drain workers (exec/core.py stop flag)
# ---------------------------------------------------------------------------

class _FakeBatch:
    def device_size_bytes(self) -> int:
        return 64


def test_early_consumer_exit_stops_drain_workers():
    from spark_rapids_tpu.exec.core import (ExecCtx, PlanNode,
                                            drain_partitions_indexed)

    full = 40          # batches a slow partition would produce if drained
    step = 0.1         # seconds per slow batch
    counts = [0, 0, 0, 0]

    class SlowNode(PlanNode):
        def __init__(self):
            super().__init__(())

        def num_partitions(self, ctx):
            return 4

        def partition_iter(self, ctx, pid):
            if pid == 0:
                yield _FakeBatch()
                return
            for _ in range(full):
                time.sleep(step)
                counts[pid] += 1
                yield _FakeBatch()

    conf = TpuConf({"spark.rapids.sql.concurrentTpuTasks": 4,
                    "spark.rapids.sql.metrics.enabled": "false"})
    with ExecCtx(backend="device", conf=conf) as ctx:
        it = drain_partitions_indexed(ctx, SlowNode())
        t0 = time.monotonic()
        pid, first = next(it)
        assert pid == 0 and isinstance(first, _FakeBatch)
        it.close()     # LIMIT satisfied / consumer gone
        elapsed = time.monotonic() - t0
        # without the stop flag the close would block for the FULL drain
        # of three slow partitions (~4s each); with it, workers stop at
        # their next batch boundary
        assert elapsed < full * step / 2, elapsed
        assert max(counts[1:]) < full, counts
        # every parked spillable batch was closed on the way out
        assert not ctx.cache["catalog"]._entries


# ---------------------------------------------------------------------------
# shuffle retry ladder: deadline aborts mid-backoff
# ---------------------------------------------------------------------------

def test_deadline_aborts_shuffle_backoff_mid_pause():
    from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
    # a port nothing listens on: every connect fails fast (refused),
    # so elapsed time is dominated by the backoff pause
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    before = get_registry().snapshot()
    lc = QueryLifecycle("qdl", timeout=0.3)
    lc.start()
    retry_wait = 2.0
    t0 = time.monotonic()
    with pytest.raises(QueryDeadlineExceeded):
        list(fetch_remote_with_retry(("127.0.0.1", port), "s1", 0,
                                     device=False, timeout=1.0,
                                     retry_wait=retry_wait, backoff=1.0,
                                     max_retries=8, lifecycle=lc))
    elapsed = time.monotonic() - t0
    # the deadline fired DURING the first backoff pause: abort well
    # under one full (jittered up to 1.5x) backoff step, not after it
    assert elapsed < 2 * retry_wait, elapsed
    assert _counter_delta(before, "queries_deadline_exceeded") == 1


# ---------------------------------------------------------------------------
# session integration: cancel / deadline / shutdown on real TPC-H plans
# ---------------------------------------------------------------------------

# same storm as tests/test_recovery_chaos.py: peer death + corrupted
# spilled shuffle output + tiny budgets, so cancellation lands while
# retries, recovery and spill I/O are all active
_STORM = ("shuffle.peer.dead:dead,times=4;"
          "spill.disk.corrupt:corrupt,priority=0,times=2")


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    d = str(tmp_path_factory.mktemp("tpch_lifecycle") / "sf001")
    generate_tpch(d, sf=0.01)
    _split_tables(d, ("lineitem", "orders", "customer"), parts=4)
    return d


def _split_tables(data_dir: str, tables, parts: int) -> None:
    """Re-write each table as ``parts`` parquet files so scans are
    multi-partition and the plans actually contain shuffle exchanges."""
    import pyarrow.parquet as pq
    for table in tables:
        path = os.path.join(data_dir, table, "part-0.parquet")
        t = pq.read_table(path)
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(data_dir, table,
                                        f"part-{i}.parquet"))


def test_cancel_mid_query_under_storm(data_dir, tmp_path, monkeypatch):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.memory import catalog as cat_mod
    from spark_rapids_tpu.session import TpuSession

    spill_dir = tmp_path / "spill"
    spill_dir.mkdir()

    # capture every DeviceSemaphore minted during the run so the
    # post-cancel capacity invariant can be asserted after ctx close
    sems = []
    orig_init = cat_mod.DeviceSemaphore.__init__

    def capture_init(self, concurrency):
        orig_init(self, concurrency)
        sems.append(self)

    monkeypatch.setattr(cat_mod.DeviceSemaphore, "__init__", capture_init)

    session = TpuSession({
        "spark.rapids.test.faults": _STORM,
        "spark.rapids.memory.tpu.spillStoreSize": 1 << 16,
        "spark.rapids.memory.host.spillStorageSize": 4096,
        "spark.rapids.memory.spill.dir": str(spill_dir),
    })
    df = build_tpch_query("q3", session, data_dir)
    outcome: list = []

    def run():
        try:
            outcome.append(("ok", df.collect()))
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            outcome.append(("err", e))

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 30.0
    while not session.active_queries() and t.is_alive() \
            and time.monotonic() < deadline:
        time.sleep(0.002)
    qids = session.active_queries()
    assert qids, "query never became active"
    time.sleep(0.3)        # let it get into the storm
    before = get_registry().snapshot()
    cancelled = session.cancel(qids[0])
    if not cancelled:
        t.join(timeout=60.0)
        pytest.skip("query finished before the cancel landed")

    t.join(timeout=60.0)   # bounded unwind, not a full run
    assert not t.is_alive(), "cancelled query did not unwind in time"
    kind, val = outcome[0]
    assert kind == "err" and isinstance(val, QueryCancelled), outcome
    # exactly one queries_cancelled no matter how many checkpoints fired;
    # post-run cancels are no-ops (the query is no longer live)
    assert not session.cancel(qids[0])
    assert session.cancel_all() == 0
    assert _counter_delta(before, "queries_cancelled") == 1
    # the unwind released the device in full and unlinked every spill file
    assert sems, "no DeviceSemaphore was ever minted"
    for sem in sems:
        assert sem._sem._value == sem.concurrency
    leftover = [os.path.join(r, f)
                for r, _d, fs in os.walk(spill_dir) for f in fs]
    assert not leftover, leftover
    assert session.active_queries() == []


def test_query_timeout_conf_enforced(data_dir):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.session import TpuSession
    session = TpuSession({"spark.rapids.sql.queryTimeout": 0.001})
    df = build_tpch_query("q6", session, data_dir)
    with pytest.raises(QueryDeadlineExceeded):
        df.collect()
    assert session.active_queries() == []


def test_hang_fault_broken_by_socket_timeout():
    """A peer that accepts the fetch then sends nothing (the
    ``shuffle.peer.hang`` fault) must be broken by the client's
    ``socketTimeout`` read deadline and retried to an EXACT result —
    not wedge the fetch for the full tcp.timeoutSeconds (120s)."""
    import numpy as np

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.exec.core import (ExecCtx, device_to_host,
                                            host_to_device)
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport

    schema = T.Schema([T.StructField("x", T.IntegerType())])
    conf = TpuConf({
        "spark.rapids.test.faults":
            "shuffle.peer.hang:hang,times=1,seconds=30",
        "spark.rapids.shuffle.socketTimeout": 0.5,
    })
    before = get_registry().snapshot()
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            oracle = []
            for m in range(4):
                vals = [m, m + 100]
                hb = HostBatch([HostColumn(np.asarray(vals, np.int32),
                                           np.ones(2, bool),
                                           T.IntegerType())], schema)
                t.write_partition(1, m, 0, host_to_device(hb))
                oracle += vals
            t0 = time.monotonic()
            got = []
            for b in fetch_remote_with_retry(t.address, 1, 0, conf=conf):
                got.extend(device_to_host(b).columns[0].to_list())
            elapsed = time.monotonic() - t0
            assert sorted(got) == sorted(oracle)
            # the stall really happened (>= the 0.5s read deadline) and
            # was broken by socketTimeout, nowhere near the hang window
            assert 0.4 <= elapsed < 15.0, elapsed
            assert _counter_delta(before, "shuffle.fetch.retries") >= 1
            assert t.server_metrics["faults_injected"] >= 1
        finally:
            t.close()


def test_shutdown_drain_finishes_inflight_then_rejects(data_dir):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.session import TpuSession
    expected = build_tpch_query(
        "q6", TpuSession({}), data_dir).collect()

    session = TpuSession({})
    df = build_tpch_query("q6", session, data_dir)
    outcome: list = []

    def run():
        try:
            outcome.append(("ok", df.collect()))
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            outcome.append(("err", e))

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 30.0
    while not session.active_queries() and t.is_alive() \
            and time.monotonic() < deadline:
        time.sleep(0.002)
    session.shutdown(drain=True, timeout=120.0)
    t.join(timeout=10.0)
    assert not t.is_alive()
    kind, val = outcome[0]
    assert kind == "ok", outcome
    assert val == expected          # drained to the EXACT result
    with pytest.raises(QueryRejected, match="shutting down"):
        df.collect()
    assert session.active_queries() == []


def test_shutdown_no_drain_cancels_inflight(data_dir):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.session import TpuSession
    session = TpuSession({
        "spark.rapids.test.faults": _STORM,
        "spark.rapids.memory.tpu.spillStoreSize": 1 << 16,
        "spark.rapids.memory.host.spillStorageSize": 4096,
    })
    df = build_tpch_query("q3", session, data_dir)
    outcome: list = []

    def run():
        try:
            outcome.append(("ok", df.collect()))
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            outcome.append(("err", e))

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 30.0
    while not session.active_queries() and t.is_alive() \
            and time.monotonic() < deadline:
        time.sleep(0.002)
    time.sleep(0.2)
    session.shutdown(drain=False)
    t.join(timeout=60.0)
    assert not t.is_alive()
    kind, val = outcome[0]
    # either the cancel landed (the common case) or the query won the
    # race and finished; both leave the session idle and closed to
    # new work
    assert kind == "ok" or isinstance(val, QueryCancelled), outcome
    assert session.active_queries() == []
    with pytest.raises(QueryRejected):
        df.collect()

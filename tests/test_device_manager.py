"""Device manager: fail-fast init, version gate, HBM pool math.

Reference: GpuDeviceManager.scala:120-262 (init + computeRmmInitSizes),
Plugin.scala:146-201 (fail-fast executor init + version check with
override flag).
"""
import time

import pytest

from spark_rapids_tpu import device as D
from spark_rapids_tpu.conf import TpuConf


@pytest.fixture(autouse=True)
def fresh_state():
    D._reset_for_tests()
    yield
    D._reset_for_tests()
    # leave the process initialized for later tests in the session
    D.initialize_device(TpuConf({}))


def test_initialize_populates_info():
    D.initialize_device(TpuConf({}))
    info = D.device_info()
    assert info["initialized"]
    assert info["device_count"] >= 1
    assert info["platform"] == "cpu"  # conftest pins the CPU backend


def test_init_timeout_fails_fast():
    conf = TpuConf({"spark.rapids.tpu.initTimeoutSeconds": 1})
    with pytest.raises(D.TpuInitError, match="did not complete"):
        D.initialize_device(conf, probe=lambda: time.sleep(30))


def test_init_probe_error_fails_fast():
    def boom():
        raise RuntimeError("PJRT exploded")
    with pytest.raises(D.TpuInitError, match="PJRT exploded"):
        D.initialize_device(TpuConf({}), probe=boom)


def test_version_gate_and_override(monkeypatch):
    import jax
    monkeypatch.setattr(jax, "__version__", "0.3.0")
    with pytest.raises(D.TpuInitError, match="jax 0.3.0"):
        D.initialize_device(TpuConf({}))
    # override flag continues with a warning (reference Plugin.scala:198)
    conf = TpuConf({"spark.rapids.tpu.allowIncompatibleRuntime": True})
    with pytest.warns(RuntimeWarning, match="incompatible runtime"):
        D.initialize_device(conf)
    assert D.device_info()["initialized"]


def test_pool_limit_math():
    # 16 GB HBM, 75% alloc fraction, 256 MB reserve
    got = D._compute_pool_limit(16 << 30, 0.75, 256 << 20)
    assert got == int((16 << 30) * 0.75) - (256 << 20)
    # degenerate budget floors at 64 MB instead of going negative
    assert D._compute_pool_limit(1 << 20, 0.5, 1 << 30) == 64 << 20


def test_catalog_uses_device_pool_limit():
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    D.initialize_device(TpuConf({}))
    # CPU backend exposes no bytes_limit: simulate an initialized TPU
    D._State.hbm_bytes_limit = 8 << 30
    D._State.pool_limit = D._compute_pool_limit(8 << 30, 0.75, 256 << 20)
    cat = BufferCatalog(conf=TpuConf({}))
    assert cat.device_limit == D._State.pool_limit
    # an explicit spillStoreSize always wins over the derived budget
    cat2 = BufferCatalog(conf=TpuConf(
        {"spark.rapids.memory.tpu.spillStoreSize": 123 << 20}))
    assert cat2.device_limit == 123 << 20
    cat.close()
    cat2.close()

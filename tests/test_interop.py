"""ML interop: device-batch export, jax/torch handoff, jax import.

Reference: ColumnarRdd.scala:42-49 + InternalColumnarRddConverter
(export RDD[Table] for XGBoost, docs/ml-integration.md:8-11) — here the
exported unit is the engine's device ColumnBatch / jax arrays.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([T.StructField("k", T.IntegerType()),
                   T.StructField("v", T.DoubleType()),
                   T.StructField("s", T.StringType())])


def _df(s, n=100, parts=2):
    return s.from_pydict(
        {"k": list(range(n)),
         "v": [None if i % 10 == 3 else float(i) * 0.5 for i in range(n)],
         "s": [f"r{i}" for i in range(n)]},
        SCHEMA, partitions=parts, rows_per_batch=16)


def test_device_batches_stay_on_device():
    import jax
    s = TpuSession({})
    total = 0
    for b in _df(s).device_batches():
        from spark_rapids_tpu.columnar.batch import ColumnBatch
        assert isinstance(b, ColumnBatch)
        assert isinstance(b.columns[0].data, jax.Array)
        total += b.host_num_rows()
    assert total == 100


def test_to_jax_values_and_validity():
    s = TpuSession({})
    out = _df(s).to_jax()
    assert set(out) == {"k", "v"}  # strings skipped by default
    vals, valid = out["v"]
    assert vals.shape == (100,) and valid.shape == (100,)
    arr = np.asarray(vals)
    mask = np.asarray(valid)
    assert not mask[3] and mask[4]
    assert arr[4] == pytest.approx(2.0)
    ks = np.asarray(out["k"][0])
    assert sorted(ks.tolist()) == list(range(100))


def test_to_jax_after_query_and_strings():
    s = TpuSession({})
    df = _df(s).where(col("k") < 10)
    out = df.to_jax(include_strings=True)
    assert len(out["s"]) == 10
    assert set(out["s"]) == {f"r{i}" for i in range(10)}


def test_to_torch():
    torch = pytest.importorskip("torch")
    s = TpuSession({})
    out = _df(s, n=20).to_torch()
    assert isinstance(out["v"], torch.Tensor)
    assert out["v"].shape == (20,)
    assert bool(out["v__valid"][3]) is False
    assert out["k"].dtype == torch.int32


def test_from_jax_roundtrip():
    import jax.numpy as jnp
    from spark_rapids_tpu.interop import from_jax

    s = TpuSession({})
    df = from_jax(s, {
        "a": jnp.arange(8, dtype=jnp.int32),
        "b": (jnp.linspace(0.0, 1.0, 8),
              jnp.asarray([True] * 7 + [False])),
    })
    rows = sorted(df.collect())
    assert rows[0] == (0, 0.0)
    assert rows[-1] == (7, None)
    assert df.schema.field("a").data_type == T.IntegerType()


def test_to_jax_empty_result():
    s = TpuSession({})
    out = _df(s).where(col("k") < 0).to_jax()
    assert out["k"][0].shape == (0,)
    assert out["v"][1].shape == (0,)

"""TPC-DS slice: generator sanity + all queries verify vs host oracle.

Reference test pattern: tpcds_test.py wraps TpcdsLikeSpark queries as
assertions (integration_tests/src/main/python/tpcds_test.py).
"""
import os

import pytest

from spark_rapids_tpu.bench.tpcds_gen import generate_tpcds, table_row_counts
from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpcds_queries import QUERIES


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcds") / "sf001")
    generate_tpcds(d, sf=0.01)
    return d


def test_row_counts_scale():
    c1 = table_row_counts(1.0)
    c10 = table_row_counts(10.0)
    assert c1["store_sales"] == 2_880_000
    assert c10["store_sales"] == 28_800_000
    assert c1["date_dim"] == c10["date_dim"] == 73049
    assert c10["customer"] > c1["customer"]


def test_generator_is_deterministic(tmp_path):
    import pyarrow.parquet as pq
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    generate_tpcds(d1, sf=0.001, tables=["item"])
    generate_tpcds(d2, sf=0.001, tables=["item"])
    t1 = pq.read_table(os.path.join(d1, "item"))
    t2 = pq.read_table(os.path.join(d2, "item"))
    assert t1.equals(t2)


def test_date_dim_keys(data_dir):
    import pyarrow.parquet as pq
    dd = pq.read_table(os.path.join(data_dir, "date_dim"))
    rows = dd.to_pydict()
    i = rows["d_date_sk"].index(2450816)  # 1998-01-02 per dsdgen convention
    assert rows["d_year"][i] == 1998
    assert rows["d_moy"][i] == 1


# Default (premerge) runs a representative cross-section of plan
# shapes; TPCDS_FULL=1 sweeps all 99 (the nightly tier — the committed
# artifact artifacts/tpcds_99_sf001_verify.txt records a full pass).
# Mirrors the reference's premerge-vs-nightly split (jenkins/).
_SMOKE = ["q1", "q6", "q14", "q23", "q36", "q47", "q49", "q51", "q64",
          "q67", "q72", "q77", "q87", "q95"]
_SUITE = sorted(QUERIES) if os.environ.get("TPCDS_FULL") == "1" else _SMOKE


@pytest.mark.parametrize("query", _SUITE)
def test_query_device_matches_oracle(data_dir, query):
    reports = run_benchmark(data_dir, 0.01, [query], verify=True,
                            generate=False)
    r = reports[0]
    assert "error" not in r, r
    assert r["ok"], r


def test_all_99_queries_registered():
    assert len(QUERIES) == 99
    assert all(f"q{i}" in QUERIES for i in range(1, 100))


def test_q6_returns_states_at_larger_sf(tmp_path):
    d = str(tmp_path / "sf01")
    generate_tpcds(d, sf=0.1)
    reports = run_benchmark(d, 0.1, ["q6"], verify=True, generate=False)
    r = reports[0]
    assert r["ok"], r
    assert r["rows"] > 0, "q6 should produce state groups at SF0.1"

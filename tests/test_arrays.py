"""ArrayType columns: ingest, extract, size, contains, explode.

Reference: cuDF LIST columns + complexTypeExtractors (GetArrayItem) and
GpuGenerateExec explode (SURVEY §2.4).  Device arrays use the padded
element-matrix + lengths layout (same static-shape design as strings).
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.collections import (ArrayContains, GetArrayItem,
                                               Size)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType()),
    T.StructField("a", T.ArrayType(T.IntegerType())),
    T.StructField("d", T.ArrayType(T.DoubleType())),
])


def _df(s, n=40):
    rng = np.random.default_rng(21)
    return s.from_pydict(
        {"k": list(range(n)),
         "a": [None if i % 9 == 4 else
               [int(x) for x in rng.integers(-5, 20, i % 6)]
               for i in range(n)],
         "d": [[float(i), i * 0.5] for i in range(n)]},
        SCHEMA, partitions=2, rows_per_batch=8)


def _both(df):
    dev = sorted(df.collect(), key=str)
    ov, meta = df._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, df._s.conf), key=str)
    assert dev == host
    return dev


def test_array_roundtrip_collect():
    s = TpuSession({})
    rows = _both(_df(s))
    assert len(rows) == 40
    by_k = {r[0]: r for r in rows}
    assert by_k[4][1] is None           # null array survives
    assert by_k[0][1] == []             # empty array survives
    assert by_k[1][2] == [1.0, 0.5]


def test_get_array_item_and_size():
    s = TpuSession({})
    out = _df(s).select(
        col("k"),
        GetArrayItem(col("a"), lit(0)).alias("first"),
        GetArrayItem(col("a"), col("k") % lit(3)).alias("dyn"),
        GetArrayItem(col("d"), lit(1)).alias("d1"),
        Size(col("a")).alias("sz"))
    rows = _both(out)
    by_k = {r[0]: r for r in rows}
    assert by_k[4][1] is None and by_k[4][4] == -1   # null arr: null / -1
    assert by_k[0][1] is None and by_k[0][4] == 0    # empty arr: OOB -> null
    assert by_k[1][3] == 0.5
    for k, first, dyn, d1, sz in rows:
        if sz is not None and sz > 0 and first is not None:
            assert isinstance(first, int)


def test_array_contains():
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.ArrayType(T.LongType()))])
    df = s.from_pydict({"a": [[1, 2, 3], [4, 5], None, []]}, schema)
    out = df.select(ArrayContains(col("a"), lit(2)).alias("has2"))
    rows = _both(out)
    assert sorted(rows, key=str) == sorted(
        [(True,), (False,), (None,), (False,)], key=str)


@pytest.mark.parametrize("outer,pos", [(False, False), (True, True)])
def test_explode_array(outer, pos):
    s = TpuSession({})
    out = _df(s).explode(col("a"), output_name="e", pos=pos, outer=outer)
    rows = _both(out)
    # element count: sum of lengths (+1 per null/empty row when outer)
    base = _df(s).collect()
    want = sum(len(r[1]) for r in base if r[1] is not None)
    if outer:
        want += sum(1 for r in base if r[1] is None or r[1] == [])
    assert len(rows) == want
    if pos:
        # pos column precedes the element column
        for r in rows:
            if r[-1] is not None:
                assert r[-2] is not None


def test_array_keys_rejected():
    s = TpuSession({})
    df = _df(s)
    with pytest.raises(ValueError, match="array"):
        df.order_by(("a", True)).collect()
    with pytest.raises(ValueError, match="array"):
        from spark_rapids_tpu.expr.aggregates import CountStar
        df.group_by("a").agg(CountStar().alias("c")).collect()


def test_array_arrow_roundtrip(tmp_path):
    """Arrow export/import and the parquet scan path carry list columns
    (device matrix <-> Arrow ListArray)."""
    import pyarrow.parquet as pq
    s = TpuSession({})
    table = _df(s).to_arrow()
    assert table.num_rows == 40
    p = str(tmp_path / "arr.parquet")
    pq.write_table(table, p)
    back = s.read_parquet(p)
    rows = _both(back.select(col("k"), Size(col("a")).alias("sz")))
    assert len(rows) == 40


def test_array_cache_roundtrip():
    s = TpuSession({})
    cached = _df(s).cache()
    assert sorted(cached.collect(), key=str) == \
        sorted(_df(s).collect(), key=str)

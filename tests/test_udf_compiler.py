"""Python-UDF compiler: bytecode -> native expressions, silent fallback.

Round-3 verdict item 8 (reference udf-compiler CatalystExpressionBuilder
compile :66, silent-fallback LogicalPlanRules :79-94).
"""
import sys

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.udf import PythonUDF, compile_udf, udf


requires_py311 = pytest.mark.skipif(
    sys.version_info[:2] != (3, 11),
    reason="udf compiler targets CPython 3.11 bytecode (opcode table "
           "differs on this interpreter)")


def _session(compiler=True):
    return TpuSession({"spark.rapids.sql.udfCompiler.enabled": compiler})


def _df(s):
    schema = T.Schema([T.StructField("a", T.DoubleType()),
                       T.StructField("b", T.DoubleType())])
    return s.from_pydict({"a": [1.0, 2.0, None, -4.5],
                          "b": [10.0, 20.0, 30.0, 40.0]}, schema)


@requires_py311
def test_compile_straight_line():
    tree = compile_udf(lambda x: x * 2 + 1, [col("a")])
    assert tree is not None
    assert "Add" in repr(type(tree)) or "Add" in repr(tree)


@requires_py311
def test_compile_two_args_and_abs():
    assert compile_udf(lambda x, y: abs(x - y), [col("a"), col("b")]) \
        is not None
    assert compile_udf(lambda x: x ** 2, [col("a")]) is not None
    assert compile_udf(lambda x, y: x >= y, [col("a"), col("b")]) is not None


def test_unsupported_returns_none():
    assert compile_udf(lambda x: len(str(x)), [col("a")]) is None
    assert compile_udf(lambda x: [x], [col("a")]) is None
    # loops stay unsupported (backward jump)
    def looping(x):
        t = 0.0
        for _ in range(3):
            t = t + x
        return t
    assert compile_udf(looping, [col("a")]) is None


@requires_py311
def test_compile_branches():
    """Round-4 verdict item 6: CFG branches compile to If trees
    (reference CFG.scala + Instruction.scala conditional handling)."""
    assert compile_udf(lambda x: x if x > 0 else -x, [col("a")]) \
        is not None
    assert compile_udf(lambda x, y: x + 1 if x > y else y - 1,
                       [col("a"), col("b")]) is not None
    assert compile_udf(lambda x, y: 1.0 if (x > 0 and y > 0) else 0.0,
                       [col("a"), col("b")]) is not None
    assert compile_udf(lambda x, y: 1.0 if (x > 0 or y > 0) else 0.0,
                       [col("a"), col("b")]) is not None
    assert compile_udf(
        lambda x: 0.0 if x < 0 else (1.0 if x < 10 else 2.0),
        [col("a")]) is not None


@requires_py311
def test_branch_udf_matches_interpreter():
    """Compiled branchy UDF runs on device and matches the row-at-a-time
    interpreter, including null inputs (null in -> null out guard)."""
    fns = [
        (lambda x: x if x > 0 else -x, 1),
        (lambda x, y: x + 1 if x > y else y - 1, 2),
        (lambda x, y: 1.0 if (x > 0 and y > 0) else 0.0, 2),
        (lambda x: 0.0 if x < 0 else (1.0 if x < 3 else 2.0), 1),
    ]
    for fn, nargs in fns:
        args = [col("a"), col("b")][:nargs]
        on = _df(_session(compiler=True)).select(
            udf(fn, T.DoubleType())(*args).alias("u"))
        off = _df(_session(compiler=False)).select(
            udf(fn, T.DoubleType())(*args).alias("u"))
        assert "PythonUDF" not in on.explain()
        assert on.collect() == off.collect(), fn


@requires_py311
def test_compiled_udf_runs_on_device():
    s = _session(compiler=True)
    out = _df(s).select(col("a"),
                        udf(lambda x: x * 2 + 1)(col("a")).alias("u"))
    ex = out.explain()
    assert "PythonUDF" not in ex       # compiled away
    assert "!" not in ex               # fully on device
    rows = sorted(out.collect(), key=str)
    assert (1.0, 3.0) in rows and (2.0, 5.0) in rows
    assert any(r[0] is None and r[1] is None for r in rows)


def test_uncompilable_falls_back_to_host():
    s = _session(compiler=True)
    f = udf(lambda x: float(len(f"{x:.2f}")), T.DoubleType())
    out = _df(s).select(f(col("b")).alias("u"))
    assert "!" in out.explain()        # host fallback visible
    rows = sorted(out.collect())
    assert rows[0][0] == 5.0           # "10.00"


def test_compiler_disabled_stays_host():
    s = _session(compiler=False)
    out = _df(s).select(udf(lambda x: x * 2 + 1)(col("a")).alias("u"))
    assert "PythonUDF" in out.explain()
    assert "!" in out.explain()
    rows = sorted(out.collect(), key=str)
    assert (3.0,) in rows and (5.0,) in rows


def test_compiled_matches_host_oracle():
    s = _session(compiler=True)
    out = _df(s).select(
        udf(lambda x, y: abs(x - y) * 2)(col("a"), col("b")).alias("u"),
        udf(lambda x: -x + 0.5)(col("b")).alias("v"))
    dev = sorted(out.collect(), key=str)
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert dev == host


def test_filter_with_compiled_udf():
    s = _session(compiler=True)
    pred = udf(lambda x: x > 15.0, T.BooleanType())
    out = _df(s).where(pred(col("b")).cast(T.BooleanType()))
    rows = out.collect()
    assert all(r[1] > 15.0 for r in rows) and len(rows) == 3

"""Shuffle transport SPI + compression codecs + serializer.

Round-3 verdict item 6: transport.class must load a REAL class by
reflection, compression.codec must have implementations, and no conf may
reference nonexistent code (reference RapidsShuffleTransport.scala:
638-658, TableCompressionCodec.scala:137).
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import (SHUFFLE_TRANSPORT_CLASS, TpuConf,
                                   registered_entries)
from spark_rapids_tpu.exec.basic import LocalScanExec
from spark_rapids_tpu.exec.core import ExecCtx, device_to_host
from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
from spark_rapids_tpu.exec.partitioning import HashPartitioning
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.shuffle import make_transport
from spark_rapids_tpu.shuffle.compression import get_codec


def _scan(n=500):
    data = {"k": list(range(n)),
            "v": [float(i) * 0.5 for i in range(n)],
            "s": [f"row-{i % 37}" for i in range(n)]}
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("v", T.DoubleType()),
                       T.StructField("s", T.StringType())])
    return LocalScanExec.from_pydict(data, schema, 2, n // 2)


def _rows(plan, ctx):
    out = []
    for b in plan.execute(ctx):
        hb = device_to_host(b) if ctx.is_device else b
        out.extend(zip(*[c.to_list() for c in hb.columns]))
    return sorted(out, key=str)


@pytest.mark.parametrize("codec", ["lz4", "zstd"])
def test_codec_roundtrip(codec):
    if codec == "zstd":
        pytest.importorskip("zstandard")
    c = get_codec(codec)
    rng = np.random.default_rng(3)
    for payload in (b"", b"xyz" * 1000,
                    rng.integers(0, 255, 65536, dtype=np.uint8).tobytes()):
        z = c.compress(payload)
        assert c.decompress(z, len(payload)) == payload
    # compressible data actually compresses
    big = b"spark-rapids-tpu " * 4096
    assert len(c.compress(big)) < len(big) // 4


def test_codec_unknown_rejected():
    with pytest.raises(ValueError):
        get_codec("snappy")


def test_default_transport_class_loads():
    """The conf default must reference code that exists (round-2 verdict:
    it pointed at a nonexistent module)."""
    conf = TpuConf({})
    tr = make_transport(conf, None)
    from spark_rapids_tpu.shuffle.local import LocalShuffleTransport
    assert isinstance(tr, LocalShuffleTransport)


def test_reflection_loads_custom_transport():
    conf = TpuConf({SHUFFLE_TRANSPORT_CLASS.key:
                    "test_shuffle_transport.RecordingTransport"})
    tr = make_transport(conf, None)
    assert isinstance(tr, RecordingTransport)
    with pytest.raises(ValueError):
        make_transport(TpuConf({SHUFFLE_TRANSPORT_CLASS.key: "no.such.Cls"}))


class RecordingTransport:
    """Minimal SPI impl used by the reflection test."""

    def __init__(self, conf, ctx):
        self.written = []

    def write_partition(self, shuffle_id, map_id, part_id, batch):
        self.written.append((shuffle_id, map_id, part_id, batch))

    def fetch_partition(self, shuffle_id, part_id):
        return iter([b for s, m, p, b in self.written if p == part_id])

    def close(self):
        pass


@pytest.mark.parametrize("codec", ["none", "lz4", "zstd"])
def test_exchange_through_codec(codec):
    """End-to-end exchange with each codec matches the host oracle."""
    if codec == "zstd":
        pytest.importorskip("zstandard")
    plan = ShuffleExchangeExec(HashPartitioning([col("k")], 3), _scan())
    conf = TpuConf({"spark.rapids.shuffle.compression.codec": codec})
    with ExecCtx(backend="device", conf=conf) as ctx:
        dev = _rows(plan, ctx)
        if codec != "none":
            tr = next(v for k, v in ctx.cache.items()
                      if isinstance(k, tuple) and k[0] == "shuffle")
            assert tr.metrics["bytes_compressed"] > 0
            assert tr.metrics["bytes_compressed"] < \
                tr.metrics["bytes_written"]
    host = _rows(plan, ExecCtx(backend="host"))
    assert dev == host


def test_metadata_size_enforced():
    from spark_rapids_tpu.shuffle.serializer import serialize_batch
    from spark_rapids_tpu.exec.core import host_to_device
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    cols = [HostColumn(np.arange(4), np.ones(4, np.bool_), T.LongType())]
    b = host_to_device(HostBatch(cols, T.Schema(
        [T.StructField("x", T.LongType())])))
    with pytest.raises(ValueError, match="maxMetadataSize"):
        serialize_batch(b, max_metadata_size=8)


def test_no_conf_references_missing_code():
    """Every registered conf default that names a python object resolves
    (round-2 verdict: dead confs advertising unbuilt features)."""
    import importlib
    for key, entry in registered_entries().items():
        d = entry.default
        if isinstance(d, str) and d.count(".") >= 2 and \
                d.replace(".", "").replace("_", "").isalnum() \
                and d[0].isalpha() and not d[0].isupper():
            mod, _, cls = d.rpartition(".")
            try:
                m = importlib.import_module(mod)
            except ImportError:
                continue  # not a python path (e.g. a file path)
            assert hasattr(m, cls), f"{key} references missing {d}"


def test_fetch_partition_early_break_unpins():
    """A consumer breaking out of fetch_partition mid-iteration (the
    adaptive skew reader's group boundary) must not leave batches pinned
    (review finding: pin leaked on GeneratorExit)."""
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    from spark_rapids_tpu.shuffle.local import LocalShuffleTransport

    schema = T.Schema([T.StructField("x", T.IntegerType())])
    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = LocalShuffleTransport(conf, ctx)
        for m in range(3):
            hb = HostBatch([HostColumn(
                np.arange(4, dtype=np.int32) + m, np.ones(4, bool),
                T.IntegerType())], schema)
            t.write_partition(7, m, 0, host_to_device(hb))
        slots = t._store[(7, 0)]
        for b in t.fetch_partition(7, 0):
            break  # abandon the generator after the first batch
        assert all(s.item[1]._pins == 0 for s in slots
                   if s.item is not None and s.item[0] == "spillable"), \
            "pin leaked on early break"
        # sliced fetch serves exactly [lo, hi)
        got = [int(b.columns[0].data[0]) for b in t.fetch_partition(
            7, 0, 1, 3)]
        assert got == [1, 2]
        t.close()

"""OOM chaos suite: TPC-H under a deterministic HBM-exhaustion storm.

The ``memory.oom.until_rows`` fault point makes every retry-scoped
dispatch above the row threshold fail exactly like an XLA
RESOURCE_EXHAUSTED, so split-and-retry (memory/retry.py) must halve
batches until they fit — queries still return EXACT oracle results,
with nonzero split counts in the BufferCatalog metrics.  Reference
intent: the plugin's retry framework keeps queries correct under
memory pressure (RmmRapidsRetryIterator + the *_retry suites); here the
pressure is seeded and conf-driven, CPU-only, no mocks.

The sync-point tests cover the async-dispatch gap: with
``_SYNC_DISPATCH`` off (tpu/axon behavior) an OOM surfaces at the
chunk-flush ``device_get`` in aggregate/join — ``retry_sync`` must
spill, redo the poisoned dispatches from retained inputs, and sync
again instead of propagating.
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch

# storm threshold: any dispatch above this row count OOMs.  TPC-H
# sf0.01 lineitem is ~60k rows per scan batch, so hot operators must
# split 2+ levels before work fits.  The 32-row minSplitRows default
# floor is far below the threshold, so splits always converge.
_STORM = "memory.oom.until_rows:oom,until_rows=16384"
_CHAOS_CONF = {
    "spark.rapids.test.faults": _STORM,
    # small host arena: chaos catalogs spill often and a 1GB mapping
    # per query is pure setup cost here
    "spark.rapids.memory.host.spillStorageSize": 64 << 20,
}

_QUERIES = ["q1", "q3", "q6", "q12", "q18"]


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_chaos") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


@pytest.mark.parametrize("query", _QUERIES)
def test_tpch_exact_under_oom_storm(data_dir, query):
    r = run_benchmark(data_dir, 0.01, [query], verify=True,
                      generate=False, suite="tpch",
                      session_conf=_CHAOS_CONF)[0]
    assert "error" not in r, r
    assert r["ok"], r
    cat = r["metrics"].get("BufferCatalog", {})
    # the storm must actually have forced split-and-retry
    assert cat.get("oom_splits", 0) > 0, cat
    assert cat.get("oom_retries", 0) >= cat["oom_splits"], cat
    assert cat.get("device_bytes_peak", 0) > 0, cat


def test_storm_inert_with_retry_disabled(data_dir):
    """Control: with oomRetry.enabled=false the legacy spill hook has
    no row context, so until_rows rules cannot fire there BY DESIGN
    (plain ctx.dispatch inside retry scopes must not storm).  The
    query runs clean with zero splits — proving the splits above are
    the retry framework's, not ambient fault noise."""
    conf = dict(_CHAOS_CONF)
    conf["spark.rapids.memory.tpu.oomRetry.enabled"] = "false"
    r = run_benchmark(data_dir, 0.01, ["q6"], verify=True,
                      generate=False, suite="tpch",
                      session_conf=conf)[0]
    assert "error" not in r and r["ok"], r
    cat = r["metrics"].get("BufferCatalog", {})
    assert cat.get("oom_splits", 0) == 0, cat


# ---------------------------------------------------------------------------
# async sync-point recovery (_SYNC_DISPATCH gap)
# ---------------------------------------------------------------------------

@pytest.fixture
def async_dispatch(monkeypatch):
    """Force the async-dispatch mode (tpu/axon behavior on CPU): OOMs
    surface at sync points, not at dispatch."""
    from spark_rapids_tpu.memory import catalog as cat_mod
    monkeypatch.setattr(cat_mod, "_SYNC_DISPATCH", False)
    yield
    # monkeypatch restores the cached value on teardown


def _session(faults: str):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.test.faults": faults})


def _oracle(df):
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = df._overridden(quiet=True)
    return sorted(collect_host(meta.exec_node, df._s.conf))


@pytest.mark.parametrize("op", ["agg_flush", "join_flush"])
def test_sync_point_oom_recovered(async_dispatch, op):
    """An OOM injected at the aggregate/join chunk-flush sync point is
    recovered by retry_sync (spill + redo + re-sync), not propagated
    (the pre-retry engine died here on async backends).  The run drives
    an explicit ExecCtx so the fault's fired count is checkable — a
    vacuous pass (injection site never reached) fails the test."""
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    from spark_rapids_tpu.expr.aggregates import CountStar, Sum
    from spark_rapids_tpu.expr.core import col

    s = _session(f"memory.oom:oom,op={op},times=1")
    schema = T.Schema([
        T.StructField("k", T.IntegerType(), True),
        T.StructField("v", T.LongType(), True),
    ])
    data = {"k": [i % 13 for i in range(500)],
            "v": list(range(500))}
    left = s.from_pydict(data, schema, partitions=2)
    if op == "agg_flush":
        df = left.group_by("k").agg(Sum(col("v")), CountStar())
    else:
        rschema = T.Schema([
            T.StructField("k", T.IntegerType(), True),
            T.StructField("w", T.LongType(), True),
        ])
        right = s.from_pydict(
            {"k": list(range(13)), "w": [i * 10 for i in range(13)]},
            rschema)
        df = left.join(right, on="k").group_by("k").agg(Sum(col("w")))
    ov, meta = df._overridden(quiet=True)
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in meta.exec_node.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        fired = ctx.catalog.faults.fired_count()
        retries = ctx.catalog.metrics["oom_retries"]
    assert sorted(rows) == _oracle(df)
    assert fired == 1 and retries == 1, (fired, retries)


def test_sync_point_fault_fires(async_dispatch):
    """The injected flush-point fault is consumed (fired), proving the
    recovery above exercised the redo path rather than never hitting
    the injection site."""
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.conf import TpuConf

    conf = TpuConf({"spark.rapids.test.faults":
                    "memory.oom:oom,op=agg_flush,times=1"})
    with ExecCtx(backend="device", conf=conf) as ctx:
        redone = []
        out = ctx.retry_sync(lambda: 41, redo=lambda: redone.append(1),
                             op="agg_flush")
        assert out == 41 and redone == [1]
        assert ctx.catalog.faults.fired_count() == 1
        assert ctx.catalog.metrics["oom_retries"] == 1

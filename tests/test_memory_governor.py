"""Cross-query HBM memory governor tests (memory/governor.py).

Covers the four tentpole behaviors — per-query accounting that sums to
catalog occupancy, need-sized ownership-aware arbitration with
wound-wait ordering, bounded lifecycle-integrated grant waits, and
pressure-shed admission — plus gate-off reversibility: with
``spark.rapids.memory.governor.enabled=false`` nothing registers and
plans / results are identical to the ungoverned engine.
"""
import threading
import time

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.memory import BufferCatalog
from spark_rapids_tpu.memory.governor import MemoryGovernor
from spark_rapids_tpu.obs.registry import get_registry

SCHEMA = T.Schema([
    T.StructField("a", T.LongType(), True),
    T.StructField("s", T.StringType(), True),
])


def _batch(rng, n=256):
    return HostBatch.from_pydict({
        "a": [int(x) for x in rng.integers(-1000, 1000, n)],
        "s": [f"str{x}" if x % 7 else None for x in rng.integers(0, 99, n)],
    }, SCHEMA).to_device()


class _SpillCat:
    """Fake catalog recording the spill sizes the governor asks for."""

    def __init__(self, device_limit=1 << 20, yields=None):
        self.device_limit = device_limit
        self.governor = None
        self.query_id = None
        self.requests: list[int] = []
        self._yields = yields  # None: free exactly what was asked

    def spill_device(self, n):
        self.requests.append(n)
        if self._yields is None:
            return n
        return self._yields.pop(0) if self._yields else 0


@pytest.fixture
def gov():
    """A private governor instance (not the process singleton) so tests
    never leak registered state into each other."""
    g = MemoryGovernor()
    yield g
    with g._cond:
        g._stop_bg_locked()
    _restore_singleton_source()


def _restore_singleton_source():
    """A private governor registered itself under the shared source
    name; hand the slot back to the process singleton (if one exists)
    instead of leaving the registry blind for the rest of the suite."""
    from spark_rapids_tpu.memory import governor as gov_mod
    if gov_mod._GOVERNOR is not None:
        get_registry().register_source("governor", gov_mod._GOVERNOR._source)
    else:
        get_registry().unregister_source("governor")


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------

def test_accounting_sums_to_catalog_occupancy(gov, rng):
    cat = BufferCatalog(device_limit=64 << 20, host_limit=1 << 24)
    gov.register(cat, "q1", None, {})
    ids = [cat.add_batch(_batch(rng), priority=i) for i in range(4)]
    st = gov.query_stats("q1")["q1"]
    assert st["device_bytes"] == cat.device_used > 0
    assert st["peak_bytes"] == cat.device_used
    # pin one entry: pinned ledger mirrors the refcount 0->1 edge
    b = cat.acquire(ids[0])
    st = gov.query_stats("q1")["q1"]
    assert st["pinned_bytes"] == b.device_size_bytes()
    cat.release(ids[0])
    assert gov.query_stats("q1")["q1"]["pinned_bytes"] == 0
    # spill moves bytes OUT of the ledger, unspill back IN
    peak = st["peak_bytes"]
    freed = cat.spill_device(cat.device_used)
    assert freed > 0
    st = gov.query_stats("q1")["q1"]
    assert st["device_bytes"] == cat.device_used
    assert st["peak_bytes"] == peak  # monotone high-water mark
    cat.acquire(ids[1])  # unspill back onto the device
    cat.release(ids[1])
    assert gov.query_stats("q1")["q1"]["device_bytes"] == cat.device_used
    # close() drains everything and unregisters
    cat.close()
    assert gov.query_stats() == {}
    assert cat.governor is None


def test_registry_source_and_ledger_verifier(gov):
    cat = _SpillCat()
    gov.register(cat, "qx", None, {})
    gov.account(cat, 1000)
    snap = get_registry().snapshot()["gauges"]
    assert snap["governor.device_bytes_total"] == 1000
    assert snap["governor.q.qx.device_bytes"] == 1000
    assert snap["governor.queries_registered"] == 1

    from spark_rapids_tpu.plan.verify import (PlanInvariantError,
                                              verify_governor_ledger)
    verify_governor_ledger(gov)  # consistent ledger passes
    st = gov._states[id(cat)]
    st.pinned_bytes = 2000  # pinned > device: impossible
    with pytest.raises(PlanInvariantError, match="pinned_bytes"):
        verify_governor_ledger(gov)
    st.pinned_bytes = 0
    st.device_bytes = -5
    with pytest.raises(PlanInvariantError, match="negative ledger"):
        verify_governor_ledger(gov)
    st.device_bytes = 100
    st.peak_bytes = 0
    with pytest.raises(PlanInvariantError, match="peak_bytes"):
        verify_governor_ledger(gov)


# ---------------------------------------------------------------------------
# arbitration: need-sized, own-first, wound-wait
# ---------------------------------------------------------------------------

def test_reclaim_is_need_sized_not_quarter_budget(gov):
    cat = _SpillCat(device_limit=1 << 30)
    gov.register(cat, "q1", None, {
        "spark.rapids.memory.governor.minSpillBytes": 4096})
    freed = gov.reclaim(cat, 100_000)
    assert freed == 100_000
    # sized to the failed allocation, NOT device_limit // 4 (256 MiB)
    assert cat.requests == [100_000]
    # tiny request hits the conf'd floor instead
    cat.requests.clear()
    gov.reclaim(cat, 1)
    assert cat.requests == [4096]


def test_ungoverned_reclaim_keeps_legacy_quarter_sweep():
    from spark_rapids_tpu.memory.retry import _reclaim
    cat = _SpillCat(device_limit=1 << 20)
    assert cat.governor is None
    _reclaim(cat, 12345)
    assert cat.requests == [(1 << 20) // 4]


def test_wound_wait_ordering(gov):
    older, younger = _SpillCat(), _SpillCat()
    gov.register(older, "old", None, {})
    gov.register(younger, "young", None, {})
    st_old = gov._states[id(older)]
    st_young = gov._states[id(younger)]
    # younger requester: the older peer is off limits
    assert gov._reclaim_from_peers(st_young, 100) == 0
    assert older.requests == []
    # older requester: the younger peer is a victim
    assert gov._reclaim_from_peers(st_old, 100) == 100
    assert younger.requests == [100]


def test_peers_pinned_working_set_never_spilled(gov, rng):
    """Real catalogs: the victim's pinned entry survives a peer
    reclaim; only its refcount==0 buffers move."""
    req = BufferCatalog(device_limit=64 << 20, host_limit=1 << 24)
    vic = BufferCatalog(device_limit=64 << 20, host_limit=1 << 24)
    gov.register(req, "older", None, {})
    gov.register(vic, "younger", None, {})
    pinned_id = vic.add_batch(_batch(rng), priority=0)
    vic.acquire(pinned_id)  # pin: the victim's working set
    idle_id = vic.add_batch(_batch(rng), priority=1)
    st_req = gov._states[id(req)]
    freed = gov._reclaim_from_peers(st_req, 1 << 20)
    assert freed > 0
    assert vic.tier_of(pinned_id) == "device"   # untouched
    assert vic.tier_of(idle_id) != "device"     # spilled
    vic.release(pinned_id)
    req.close()
    vic.close()


def test_victim_error_never_kills_requester(gov):
    class _BadCat(_SpillCat):
        def spill_device(self, n):
            raise RuntimeError("victim exploded")

    older, bad = _SpillCat(), _BadCat()
    gov.register(older, "old", None, {})
    gov.register(bad, "young", None, {})
    before = get_registry().snapshot()["counters"].get(
        "governor_victim_errors", 0)
    st_old = gov._states[id(older)]
    assert gov._reclaim_from_peers(st_old, 100) == 0  # skipped, no raise
    after = get_registry().snapshot()["counters"]["governor_victim_errors"]
    assert after == before + 1


# ---------------------------------------------------------------------------
# grant waits
# ---------------------------------------------------------------------------

def test_grant_wait_blocks_until_peer_release(gov):
    a, b = _SpillCat(device_limit=1000), _SpillCat(device_limit=1000)
    gov.register(a, "qa", None, {})
    gov.register(b, "qb", None, {})
    gov._grant_timeout = 5.0
    gov.account(a, 900)
    gov.account(b, 90)
    st_b = gov._states[id(b)]
    got = []
    t = threading.Thread(
        target=lambda: got.append(gov._wait_for_grant(b, st_b, 500)))
    t.start()
    deadline = time.monotonic() + 2.0
    while gov.reserved_bytes() != 500 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert gov.reserved_bytes() == 500  # reservation visible mid-wait
    gov.account(a, -800)                # peer releases -> grant
    t.join(3.0)
    assert not t.is_alive() and got == [500]
    assert gov.reserved_bytes() == 0


def test_grant_wait_headroom_short_circuit(gov):
    """With ledger headroom already covering the need, the OOM is
    outside the ledger's model — no wait, 0 so the split ladder runs."""
    cat = _SpillCat(device_limit=1 << 20)
    gov.register(cat, "qa", None, {})
    st = gov._states[id(cat)]
    t0 = time.monotonic()
    assert gov._wait_for_grant(cat, st, 4096) == 0
    assert time.monotonic() - t0 < 1.0


def test_grant_wait_skips_when_no_live_peer(gov):
    """A solo query has nobody to wait on: however over-committed its
    ledger, the wait returns 0 immediately so the split ladder runs
    instead of stalling out the full grant timeout."""
    cat = _SpillCat(device_limit=1000)
    gov.register(cat, "qa", None, {})
    gov._grant_timeout = 30.0
    gov.account(cat, 990)          # pinned working set over budget
    st = gov._states[id(cat)]
    t0 = time.monotonic()
    assert gov._wait_for_grant(cat, st, 500) == 0
    assert time.monotonic() - t0 < 1.0


def test_leaked_catalog_ledger_dropped_on_gc(gov):
    """A governed catalog garbage-collected without close() must not
    pin its ledger: leaked bytes would inflate aggregate occupancy for
    every later query in the process."""
    import gc
    cat = _SpillCat(device_limit=1000)
    gov.register(cat, "leaky", None, {})
    gov.account(cat, 500)
    assert "leaky" in gov.query_stats()
    del cat
    gc.collect()
    assert "leaky" not in gov.query_stats()


def test_grant_wait_times_out_bounded(gov):
    a, b = _SpillCat(device_limit=1000), _SpillCat(device_limit=1000)
    gov.register(a, "qa", None, {})
    gov.register(b, "qb", None, {})
    gov._grant_timeout = 0.2
    gov.account(a, 990)
    st_b = gov._states[id(b)]
    before = get_registry().snapshot()["counters"].get(
        "governor_grant_timeouts", 0)
    t0 = time.monotonic()
    assert gov._wait_for_grant(b, st_b, 500) == 0
    assert 0.15 < time.monotonic() - t0 < 2.0
    assert gov.reserved_bytes() == 0
    after = get_registry().snapshot()["counters"]["governor_grant_timeouts"]
    assert after == before + 1


def test_grant_wait_cancellation_releases_reservation(gov):
    """A cancel landing mid-grant-wait aborts the wait with the
    terminal error and ALWAYS releases the reservation."""
    from spark_rapids_tpu.exec.lifecycle import QueryCancelled, QueryLifecycle
    a, b = _SpillCat(device_limit=1000), _SpillCat(device_limit=1000)
    lc = QueryLifecycle("qb")
    lc.start()
    gov.register(a, "qa", None, {})
    gov.register(b, "qb", lc, {})
    gov._grant_timeout = 30.0
    gov.account(a, 990)
    st_b = gov._states[id(b)]
    err = []
    def run():
        try:
            gov._wait_for_grant(b, st_b, 500)
        except QueryCancelled as ex:
            err.append(ex)
    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 2.0
    while gov.reserved_bytes() != 500 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert gov.reserved_bytes() == 500
    lc.cancel("test cancel")
    t.join(3.0)
    assert not t.is_alive(), "grant wait must abort on cancellation"
    assert err, "terminal error must propagate, never be swallowed"
    assert gov.reserved_bytes() == 0, "reservation leaked on cancel"


# ---------------------------------------------------------------------------
# watermarks + pressure shed
# ---------------------------------------------------------------------------

def test_background_watermark_spill(gov):
    cat = _SpillCat(device_limit=1000)
    gov.register(cat, "qa", None, {})
    gov._poll_s = 0.02
    before = get_registry().snapshot()["counters"].get(
        "governor_background_spills", 0)
    gov.account(cat, 900)  # 90% > high watermark 0.85
    deadline = time.monotonic() + 3.0
    while not cat.requests and time.monotonic() < deadline:
        time.sleep(0.02)
    assert cat.requests, "background thread never spilled"
    # asked to get back under the LOW watermark: 900 - 0.65*1000
    assert cat.requests[0] == 900 - 650
    after = get_registry().snapshot()["counters"][
        "governor_background_spills"]
    assert after > before


def test_pressure_shed_pauses_admissions(gov):
    from spark_rapids_tpu.exec.lifecycle import (AdmissionController,
                                                 QueryRejected)
    cat = _SpillCat(device_limit=1000)
    gov.register(cat, "qa", None, {})
    gov._shed_hold = 0.05
    gov.account(cat, 990)  # 99% > shed watermark 0.95
    time.sleep(0.15)       # sustain past the hold
    ac = AdmissionController(max_concurrent=4)
    ac.pressure_hook = gov.admission_pressure
    with pytest.raises(QueryRejected, match="shedWatermark"):
        ac.admit("qNew")
    # pressure relief resumes admissions
    gov.account(cat, -990)
    assert gov.admission_pressure() is None
    tok = ac.admit("qNew2")
    ac.release()


def test_transient_spike_does_not_shed(gov):
    cat = _SpillCat(device_limit=1000)
    gov.register(cat, "qa", None, {})
    gov._shed_hold = 10.0
    gov.account(cat, 990)
    assert gov.admission_pressure() is None  # spike shorter than hold


# ---------------------------------------------------------------------------
# fault points
# ---------------------------------------------------------------------------

def test_governor_fault_points_registered():
    from spark_rapids_tpu.faults import KNOWN_POINTS
    assert "memory.grant.stall" in KNOWN_POINTS
    assert "memory.governor.oom_storm" in KNOWN_POINTS


def test_oom_storm_fault_denies_reclaim(gov):
    from spark_rapids_tpu.faults import FaultRegistry
    cat = _SpillCat(device_limit=1 << 20)
    cat.faults = FaultRegistry("memory.governor.oom_storm:oom,times=0")
    gov.register(cat, "qa", None, {})
    assert gov.reclaim(cat, 4096) == 0
    assert cat.requests == []  # arbitration bypassed entirely


# ---------------------------------------------------------------------------
# end-to-end wiring + gate-off reversibility
# ---------------------------------------------------------------------------

def _toy_query(session, rows=2000):
    from spark_rapids_tpu.expr.aggregates import CountStar, Sum
    from spark_rapids_tpu.expr.core import col
    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.LongType(), True)])
    df = session.from_pydict({"k": [i % 7 for i in range(rows)],
                              "v": list(range(rows))}, schema, partitions=2)
    return df.group_by("k").agg(Sum(col("v")), CountStar())


def test_execctx_registers_and_explain_carries_governor_line():
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    from spark_rapids_tpu.memory.governor import get_governor
    from spark_rapids_tpu.plan.overrides import explain_analyze
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({})
    dfa = _toy_query(s)
    ov, meta = dfa._overridden(quiet=True)
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in meta.exec_node.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        gov = get_governor()
        stats = gov.query_stats(ctx.query_id)
        assert ctx.query_id in stats
        cat = ctx.cache.get("catalog")
        assert cat.governor is gov
        assert stats[ctx.query_id]["device_bytes"] == cat.device_used
        assert stats[ctx.query_id]["peak_bytes"] > 0
        txt = explain_analyze(meta.exec_node, ctx)
        assert any(l.startswith("governor: ") for l in txt.splitlines())
    # close() unregistered the ledger
    assert ctx.query_id not in get_governor().query_stats()
    assert len(rows) == 7
    s.shutdown(drain=True)


def test_gate_off_is_byte_identical():
    """enabled=false: no registration, legacy spill paths, identical
    plans and results to the governed run of the same query."""
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    from spark_rapids_tpu.session import TpuSession

    def run(conf):
        s = TpuSession(conf)
        dfa = _toy_query(s)
        ov, meta = dfa._overridden(quiet=True)
        plan_str = meta.exec_node.tree_string()
        with ExecCtx(backend="device", conf=s.conf) as ctx:
            rows = []
            for b in meta.exec_node.execute(ctx):
                rows.extend(_rows_from_host(device_to_host(b)))
            gov_attr = ctx.cache.get("catalog").governor
        s.shutdown(drain=True)
        return sorted(rows), plan_str, gov_attr

    rows_on, plan_on, gov_on = run({})
    rows_off, plan_off, gov_off = run(
        {"spark.rapids.memory.governor.enabled": "false"})
    assert gov_on is not None
    assert gov_off is None, "gate-off must not register a governor"
    assert rows_on == rows_off
    assert plan_on == plan_off

"""Session/DataFrame API + planner tests: lowering, overrides tagging,
fallback transitions, explain (reference GpuOverrides/RapidsMeta
behavior, SURVEY.md §3.2)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import Average, CountStar, Max, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import (RowNumber, WindowExpression,
                                          WindowSpec)
from spark_rapids_tpu.testing import _sort_key

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("s", T.StringType(), True),
])


def _df(s, rng, n=200, parts=3):
    return s.from_pydict({
        "k": [None if rng.random() < 0.05 else int(x)
              for x in rng.integers(0, 20, n)],
        "v": [int(x) for x in rng.integers(-100, 100, n)],
        "s": [f"s{x}" if x % 5 else None for x in rng.integers(0, 30, n)],
    }, SCHEMA, partitions=parts, rows_per_batch=64)


def test_select_filter_collect(rng):
    s = TpuSession()
    df = _df(s, rng)
    rows = df.where(col("v") > lit(0)) \
             .select(col("k"), (col("v") * lit(2)).alias("v2")) \
             .collect()
    assert rows and all(r[1] > 0 and r[1] % 2 == 0 for r in rows)


def test_group_by_agg_multi_partition(rng):
    s = TpuSession({"spark.rapids.sql.shuffle.partitions": 4})
    df = _df(s, rng)
    rows = df.group_by("k").agg(Sum(col("v")).alias("sv"),
                                CountStar().alias("c"),
                                Average(col("v")).alias("a")).collect()
    # oracle via pure python
    raw = _df(s, rng2 := np.random.default_rng(42), n=200).collect()
    # recompute from the same generated data
    import collections
    acc = collections.defaultdict(lambda: [0, 0])
    for k, v, _s in raw:
        acc[k][0] += v
        acc[k][1] += 1
    want = sorted(((k, a[0], a[1], a[0] / a[1]) for k, a in acc.items()),
                  key=_sort_key)
    got = sorted(rows, key=_sort_key)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1] and g[2] == w[2]
        assert abs(g[3] - w[3]) < 1e-9


def test_join_api(rng):
    s = TpuSession()
    a = _df(s, rng, n=100)
    b = s.from_pydict({"k2": [1, 2, 3], "name": ["a", "b", "c"]},
                      T.Schema([T.StructField("k2", T.IntegerType(), True),
                                T.StructField("name", T.StringType(), True)]))
    rows = a.join(b, on=[("k", "k2")], how="inner").collect()
    assert all(r[0] == r[3] for r in rows)


def test_sort_limit(rng):
    s = TpuSession()
    df = _df(s, rng)
    rows = df.order_by(("v", False)).limit(5).collect()
    assert len(rows) == 5
    vs = [r[1] for r in rows]
    assert vs == sorted(vs, reverse=True)


def test_window_in_select(rng):
    s = TpuSession()
    df = _df(s, rng, n=60)
    spec = WindowSpec((col("k"),), ((col("v"), True),))
    rows = df.select(col("k"), col("v"),
                     WindowExpression(RowNumber(), spec).alias("rn")
                     ).collect()
    # row numbers within each k start at 1
    by_k = {}
    for k, v, rn in rows:
        by_k.setdefault(k, []).append(rn)
    for k, rns in by_k.items():
        assert sorted(rns) == list(range(1, len(rns) + 1))


def test_explain_and_fallback(rng):
    s = TpuSession({"spark.rapids.sql.exec.FilterExec": "false"})
    df = _df(s, rng).where(col("v") > lit(0)).select(col("k"))
    text = df.explain()
    assert "! FilterExec" in text
    assert "spark.rapids.sql.exec.FilterExec is disabled" in text
    assert "* ProjectExec" in text
    # result still correct through the host fallback + transition
    rows = df.collect()
    all_rows = _df(s, np.random.default_rng(42)).collect()
    assert len(rows) == sum(1 for r in all_rows if r[1] > 0)


def test_expression_fallback_key(rng):
    s = TpuSession({"spark.rapids.sql.expression.GreaterThan": "false"})
    df = _df(s, rng).where(col("v") > lit(0))
    assert "! FilterExec" in df.explain()
    assert df.count() > 0


def test_sql_disabled_runs_host(rng):
    s = TpuSession({"spark.rapids.sql.enabled": "false"})
    df = _df(s, rng).select(col("k"))
    text = df.explain()
    assert "*" not in text.split()[0]
    assert df.count() == 200


def test_with_column_union_repartition(rng):
    s = TpuSession()
    df = _df(s, rng, n=50)
    d2 = df.with_column("w", col("v") + lit(1))
    assert d2.columns == ["k", "v", "s", "w"]
    u = d2.union(d2)
    assert u.count() == 100
    r = d2.repartition(4, "k")
    assert sorted(r.collect(), key=_sort_key) == \
        sorted(d2.collect(), key=_sort_key)


def test_to_arrow_roundtrip(rng):
    s = TpuSession()
    df = _df(s, rng, n=30)
    tbl = df.to_arrow()
    assert tbl.num_rows == 30
    df2 = s.from_arrow(tbl)
    assert sorted(df2.collect(), key=_sort_key) == \
        sorted(df.collect(), key=_sort_key)


def test_write_parquet_via_session(rng, tmp_path):
    s = TpuSession()
    df = _df(s, rng, n=40)
    out = str(tmp_path / "out")
    df.write_parquet(out)
    back = s.read_parquet(out)
    assert sorted(back.collect(), key=_sort_key) == \
        sorted(df.collect(), key=_sort_key)


def test_config_docs_generation():
    """generate_docs renders every public conf (RapidsConf.help analog)."""
    from spark_rapids_tpu.conf import generate_docs, registered_entries
    md = generate_docs()
    for key, e in registered_entries().items():
        if not e.internal:
            assert f"`{key}`" in md, key


def test_profile_trace_dir(tmp_path):
    """spark.rapids.tpu.profile.dir records an xprof trace."""
    import os
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.expr.core import col
    d = str(tmp_path / "trace")
    s = TpuSession({"spark.rapids.tpu.profile.dir": d})
    schema = T.Schema([T.StructField("x", T.IntegerType())])
    s.from_pydict({"x": [1, 2, 3]}, schema).select(col("x") + 1).collect()
    # jax writes plugins/profile/<ts>/*.xplane.pb under the dir
    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert any(f.endswith(".xplane.pb") or "trace" in f for f in found), found


def test_assert_on_tpu_test_mode():
    """spark.rapids.sql.test.enabled asserts the whole plan is on the
    device (reference assertIsOnTheGpu, GpuTransitionOverrides:322-367);
    allowedNonTpu whitelists named execs."""
    schema = T.Schema([T.StructField("v", T.LongType())])
    data = {"v": list(range(20))}

    # fully-on-device plan passes
    s = TpuSession({"spark.rapids.sql.test.enabled": True})
    assert len(s.from_pydict(data, schema).where(
        col("v") > 5).collect()) == 14

    # a disabled exec forces host fallback -> assertion fires
    s2 = TpuSession({"spark.rapids.sql.test.enabled": True,
                     "spark.rapids.sql.exec.FilterExec": False})
    df2 = s2.from_pydict(data, schema).where(col("v") > 5)
    with pytest.raises(AssertionError, match="FilterExec"):
        df2.collect()

    # ...unless whitelisted
    s3 = TpuSession({"spark.rapids.sql.test.enabled": True,
                     "spark.rapids.sql.exec.FilterExec": False,
                     "spark.rapids.sql.test.allowedNonTpu":
                         "FilterExec, LocalScanExec"})
    assert len(s3.from_pydict(data, schema).where(
        col("v") > 5).collect()) == 14

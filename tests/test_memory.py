"""Memory runtime tests: native arena, spill tiers, spillable batches.

Mirrors the reference's RapidsBufferCatalogSuite /
RapidsDeviceMemoryStoreSuite / RapidsDiskStoreSuite /
SpillableColumnarBatchSuite coverage (SURVEY.md §4.1).
"""
import threading

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.memory import (BufferCatalog, DeviceSemaphore,
                                     SpillPriority, SpillableColumnarBatch)
from spark_rapids_tpu.native import HostArena

SCHEMA = T.Schema([
    T.StructField("a", T.LongType(), True),
    T.StructField("s", T.StringType(), True),
])


def _batch(rng, n=256):
    return HostBatch.from_pydict({
        "a": [int(x) for x in rng.integers(-1000, 1000, n)],
        "s": [f"str{x}" if x % 7 else None for x in rng.integers(0, 99, n)],
    }, SCHEMA).to_device()


def _rows(b):
    return HostBatch.from_device(b).to_rows()


# ---------------------------------------------------------------------------
# native arena
# ---------------------------------------------------------------------------

def test_arena_alloc_free_coalesce():
    a = HostArena(1 << 20)
    offs = [a.alloc(1000) for _ in range(5)]
    assert all(o is not None for o in offs)
    assert a.used >= 5 * 1000
    # free middle blocks; coalescing must let a big alloc succeed
    for o in offs:
        a.free(o)
    assert a.used == 0
    big = a.alloc((1 << 20) - 64)
    assert big is not None
    a.free(big)
    with pytest.raises(ValueError):
        a.free(big)  # double free detected
    a.close()


def test_arena_view_roundtrip(tmp_path):
    a = HostArena(1 << 16)
    off = a.alloc(4096)
    data = np.arange(4096, dtype=np.uint8)
    a.view(off, 4096)[:] = data
    p = str(tmp_path / "x.bin")
    a.write_to_disk(off, 4096, p)
    off2 = a.alloc(4096)
    a.read_from_disk(off2, 4096, p)
    assert (a.view(off2, 4096) == data).all()
    a.close()


def test_arena_exhaustion_returns_none():
    a = HostArena(1 << 12)
    assert a.alloc(1 << 13) is None
    a.close()


# ---------------------------------------------------------------------------
# catalog tiers
# ---------------------------------------------------------------------------

def test_spill_to_host_and_restore(rng):
    b = _batch(rng)
    want = _rows(b)
    cat = BufferCatalog(device_limit=1, host_limit=1 << 24)
    bid = cat.add_batch(b, SpillPriority.SHUFFLE_OUTPUT)
    # over budget -> spilled immediately
    assert cat.tier_of(bid) == "host"
    got = cat.acquire(bid)
    assert cat.tier_of(bid) == "device"
    assert _rows(got) == want
    cat.release(bid)
    cat.remove(bid)
    cat.close()


def test_spill_through_to_disk(rng):
    b1, b2 = _batch(rng), _batch(rng)
    w1, w2 = _rows(b1), _rows(b2)
    size = b1.device_size_bytes()
    # host arena fits ~one batch -> second host spill pushes first to disk
    cat = BufferCatalog(device_limit=1, host_limit=size + 4096)
    id1 = cat.add_batch(b1, priority=0)
    id2 = cat.add_batch(b2, priority=1)
    assert cat.tier_of(id2) == "host"
    assert cat.tier_of(id1) == "disk"
    assert cat.metrics["host_spills"] == 1
    # restore from disk
    got1 = cat.acquire(id1)
    assert _rows(got1) == w1
    cat.release(id1)
    got2 = cat.acquire(id2)
    assert _rows(got2) == w2
    cat.release(id2)
    cat.close()


def test_pinned_buffers_do_not_spill(rng):
    b1, b2 = _batch(rng), _batch(rng)
    cat = BufferCatalog(device_limit=10 << 20, host_limit=1 << 24)
    id1 = cat.add_batch(b1, priority=0)
    _ = cat.acquire(id1)           # pin
    id2 = cat.add_batch(b2, priority=5)
    freed = cat.spill_device(1)    # must pick b2 (b1 pinned)
    assert freed > 0
    assert cat.tier_of(id1) == "device"
    assert cat.tier_of(id2) == "host"
    cat.release(id1)
    cat.close()


def test_spill_priority_order(rng):
    cat = BufferCatalog(device_limit=10 << 20, host_limit=1 << 24)
    low = cat.add_batch(_batch(rng), priority=SpillPriority.SHUFFLE_OUTPUT)
    high = cat.add_batch(_batch(rng), priority=SpillPriority.ACTIVE_BATCH)
    cat.spill_device(1)  # spills exactly one, the lowest priority
    assert cat.tier_of(low) == "host"
    assert cat.tier_of(high) == "device"
    cat.close()


def test_spillable_columnar_batch(rng):
    cat = BufferCatalog(device_limit=1, host_limit=1 << 24)
    b = _batch(rng)
    want = _rows(b)
    with SpillableColumnarBatch(b, cat) as scb:
        assert _rows(scb.get()) == want
        assert _rows(scb.get()) == want  # repeatable
    cat.close()


def test_device_semaphore_bounds_concurrency():
    sem = DeviceSemaphore(2)
    active, peak = [0], [0]
    lock = threading.Lock()

    def task():
        with sem:
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            import time
            time.sleep(0.02)
            with lock:
                active[0] -= 1

    threads = [threading.Thread(target=task) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert peak[0] <= 2


def test_oversized_buffer_spills_direct_to_disk(rng):
    b = _batch(rng, n=2048)
    want = _rows(b)
    # arena far smaller than the packed batch -> device->disk fallthrough
    cat = BufferCatalog(device_limit=1, host_limit=1 << 12)
    bid = cat.add_batch(b, 0)
    assert cat.tier_of(bid) == "disk"
    got = cat.acquire(bid)
    assert _rows(got) == want
    cat.release(bid)
    cat.close()


def test_arena_close_then_view_raises():
    a = HostArena(1 << 12)
    off = a.alloc(64)
    a.close()
    with pytest.raises(ValueError):
        a.view(off, 64)


def test_leak_check_on_close():
    """spark.rapids.memory.debug reports buffers still registered at
    catalog close (reference memory.gpu.debug leak tracking)."""
    import warnings as _w
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.core import host_to_device
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    schema = T.Schema([T.StructField("x", T.IntegerType())])
    hb = HostBatch([HostColumn(np.arange(8, dtype=np.int32),
                               np.ones(8, bool), T.IntegerType())], schema)
    cat = BufferCatalog(conf=TpuConf({"spark.rapids.memory.debug": True}))
    cat.add_batch(host_to_device(hb), priority=0)   # never released
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        cat.close()
    assert any("leak check" in str(w.message) for w in rec)
    # clean close stays silent
    cat2 = BufferCatalog(conf=TpuConf({"spark.rapids.memory.debug": True}))
    bid = cat2.add_batch(host_to_device(hb), priority=0)
    cat2.remove(bid)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        cat2.close()
    assert not any("leak check" in str(w.message) for w in rec)

"""TCP shuffle plane: in-process, cross-process, compressed, throttled.

Reference: the UCX transport module (UCX.scala:192-328 management port +
tag protocol, UCXShuffleTransport.scala:365-391 inflight throttle,
RapidsShuffleServer/Client) — multi-peer behavior is tested without a
cluster, as the reference does with mocked transports
(RapidsShuffleTestHelper.scala:26-95); here the network is real
(loopback) and the peer is a real second process.
"""
import json
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
from spark_rapids_tpu.host.batch import HostBatch, HostColumn
from spark_rapids_tpu.shuffle.tcp import (TcpShuffleTransport, fetch_remote,
                                          remote_partition_sizes)

SCHEMA = T.Schema([T.StructField("x", T.IntegerType()),
                   T.StructField("s", T.StringType())])


def _hb(vals, tags):
    return HostBatch(
        [HostColumn(np.asarray(vals, np.int32), np.ones(len(vals), bool),
                    T.IntegerType()),
         HostColumn(np.asarray(tags, object), np.ones(len(tags), bool),
                    T.StringType())], SCHEMA)


def _rows(batches):
    from spark_rapids_tpu.exec.core import device_to_host
    out = []
    for b in batches:
        hb = device_to_host(b)
        out.extend(zip(*[c.to_list() for c in hb.columns]))
    return out


@pytest.mark.parametrize("codec", ["none", "lz4"])
def test_tcp_roundtrip_in_process(codec):
    conf = TpuConf({"spark.rapids.shuffle.compression.codec": codec})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            for m in range(3):
                t.write_partition(9, m, 0, host_to_device(
                    _hb([m, m + 10], [f"a{m}", f"b{m}"])))
            t.write_partition(9, 0, 1, host_to_device(_hb([99], ["z"])))
            sizes, batch_sizes = remote_partition_sizes(t.address, 9)
            assert set(sizes) == {0, 1} and len(batch_sizes[0]) == 3
            got = _rows(fetch_remote(t.address, 9, 0))
            assert sorted(got) == sorted(
                [(0, "a0"), (10, "b0"), (1, "a1"), (11, "b1"),
                 (2, "a2"), (12, "b2")])
            # sliced fetch: only map batches [1, 3)
            got = _rows(fetch_remote(t.address, 9, 0, lo=1, hi=3))
            assert sorted(got) == sorted(
                [(1, "a1"), (11, "b1"), (2, "a2"), (12, "b2")])
        finally:
            t.close()


def test_tcp_inflight_throttle():
    """A tiny window forces server/client acks mid-stream; every frame
    still arrives intact (reference inflight-bytes throttle)."""
    conf = TpuConf({"spark.rapids.shuffle.tcp.maxBytesInFlight": 512})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            for m in range(8):
                t.write_partition(1, m, 0, host_to_device(
                    _hb(list(range(m * 50, m * 50 + 50)), ["s"] * 50)))
            # conf-driven window via the transport's own client entry
            got = _rows(t.fetch_from(t.address, 1, 0))
            assert len(got) == 400
            assert sorted(r[0] for r in got) == list(range(400))
        finally:
            t.close()


CHILD_SCRIPT = textwrap.dedent("""
    import sys, json
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport

    SCHEMA = T.Schema([T.StructField("x", T.IntegerType()),
                       T.StructField("s", T.StringType())])
    conf = TpuConf({})
    ctx = ExecCtx(backend="device", conf=conf)
    t = TcpShuffleTransport(conf, ctx)
    for m in range(4):
        hb = HostBatch(
            [HostColumn(np.arange(m * 10, m * 10 + 10, dtype=np.int32),
                        np.ones(10, bool), T.IntegerType()),
             HostColumn(np.asarray([f"m{m}r{i}" for i in range(10)],
                                   object), np.ones(10, bool),
                        T.StringType())], SCHEMA)
        t.write_partition(5, m, m % 2, host_to_device(hb))
    print(json.dumps({"port": t.address[1]}), flush=True)
    sys.stdin.readline()   # parent closes stdin when done
    t.close()
""")


def test_tcp_cross_process_fetch():
    """A REAL second process serves its map output over the wire — the
    multi-host DCN-plane shape (map side stays resident at the producer,
    reduce side pulls, RapidsShuffleClient/Server)."""
    p = subprocess.Popen([sys.executable, "-c", CHILD_SCRIPT],
                         stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    try:
        line = p.stdout.readline()
        port = json.loads(line)["port"]
        addr = ("127.0.0.1", port)
        sizes, _ = remote_partition_sizes(addr, 5)
        assert set(sizes) == {0, 1}
        even = _rows(fetch_remote(addr, 5, 0))
        odd = _rows(fetch_remote(addr, 5, 1))
        assert sorted(r[0] for r in even) == [x for m in (0, 2)
                                              for x in range(m * 10,
                                                             m * 10 + 10)]
        assert sorted(r[0] for r in odd) == [x for m in (1, 3)
                                             for x in range(m * 10,
                                                            m * 10 + 10)]
        assert ("m2r3" in [r[1] for r in even])
    finally:
        try:
            p.stdin.close()
        except OSError:
            pass
        p.wait(timeout=30)


def test_tcp_transport_via_reflection_conf():
    """The engine loads the TCP transport through transport.class and a
    shuffle query runs through it end to end."""
    from spark_rapids_tpu.exec.core import collect_host
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({
        "spark.rapids.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpShuffleTransport"})
    schema = T.Schema([T.StructField("k", T.IntegerType()),
                       T.StructField("v", T.LongType())])
    rng = np.random.default_rng(11)
    df = s.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 7, 300)],
         "v": list(range(300))}, schema, partitions=3, rows_per_batch=32)
    out = df.group_by("k").agg(Sum(col("v")).alias("sv"))
    dev = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    assert dev == sorted(collect_host(meta.exec_node, s.conf))


def test_tcp_server_error_reaches_client():
    """A store failure mid-fetch surfaces as ShuffleFetchError with the
    real cause, not a connection reset (review finding)."""
    from spark_rapids_tpu.shuffle.tcp import ShuffleFetchError

    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            def boom(*a, **k):
                raise RuntimeError("store exploded")
                yield  # pragma: no cover - generator shape
            t.fetch_partition_serialized = boom
            with pytest.raises(ShuffleFetchError, match="store exploded"):
                list(fetch_remote(t.address, 1, 0))
        finally:
            t.close()


def test_tcp_window_negotiated_from_client():
    """Server throttles at the client-declared window even when its own
    conf differs (review finding: mismatch used to deadlock)."""
    conf = TpuConf({"spark.rapids.shuffle.tcp.maxBytesInFlight": 1 << 20})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            for m in range(8):
                t.write_partition(2, m, 0, host_to_device(
                    _hb(list(range(m * 30, m * 30 + 30)), ["t"] * 30)))
            # client asks for a much smaller window than the server conf
            got = _rows(fetch_remote(t.address, 2, 0, inflight_limit=256))
            assert sorted(r[0] for r in got) == list(range(240))
        finally:
            t.close()


def test_tcp_fetch_timeout_on_stalled_peer():
    """A peer that accepts the connection but never responds raises
    ShuffleFetchError within the timeout, not a forever-hang (reference
    fetch timeout, spark.network.timeout via RapidsShuffleIterator)."""
    import socket as _socket
    import time
    from spark_rapids_tpu.shuffle.tcp import ShuffleFetchError

    srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    addr = srv.getsockname()
    try:
        t0 = time.monotonic()
        with pytest.raises(ShuffleFetchError, match="stalled"):
            list(fetch_remote(addr, 1, 0, timeout=1.5))
        assert time.monotonic() - t0 < 30
    finally:
        srv.close()


MAP_SIDE_SCRIPT = textwrap.dedent("""
    import json, sys
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.exec.partitioning import HashPartitioning
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.session import TpuSession

    # the MAP SIDE of a real plan: scan -> filter -> hash exchange,
    # executed here and SERVED to the remote reduce process
    s = TpuSession({"spark.rapids.shuffle.transport.class":
                    "spark_rapids_tpu.shuffle.tcp.TcpShuffleTransport"})
    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.LongType(), True)])
    rng = np.random.default_rng(3)
    df = s.from_pydict({"k": rng.integers(0, 13, 500).astype(np.int32),
                        "v": rng.integers(0, 1000, 500).astype(np.int64)},
                       schema, partitions=3) \
        .where(col("v") >= 100)
    ov, meta = df._overridden(quiet=True)
    ex = ShuffleExchangeExec(HashPartitioning([col("k")], 4),
                             meta.exec_node, shuffle_id=777)
    ctx = ExecCtx(backend="device", conf=s.conf)
    transport = ex._shuffled(ctx)          # runs the map side
    print(json.dumps({"port": transport.address[1]}), flush=True)
    sys.stdin.readline()
    transport.close()
""")


def test_distributed_query_two_processes():
    """VERDICT r3 item 7: a full query executes distributed — map tasks
    (scan -> filter -> hash partition) in process A served over TCP,
    reduce tasks (group-by aggregate) in process B, equal to the
    single-process run of the same plan (reference
    RapidsShuffleInternalManager.scala:285-345 write/read split)."""
    import subprocess
    import sys as _sys

    import jax

    from spark_rapids_tpu.exec.exchange import RemoteShuffleReaderExec
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.expr.aggregates import CountStar, Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.session import TpuSession

    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.LongType(), True)])

    import tempfile
    # stderr goes to a FILE, not a pipe: XLA floods stderr with
    # multi-KB warnings (e.g. AOT-cache machine-feature mismatches)
    # and an unread 64KB stderr pipe blocks the child BEFORE it prints
    # the port line — deadlocking the whole test
    err = tempfile.TemporaryFile(mode="w+")
    p = subprocess.Popen([_sys.executable, "-c", MAP_SIDE_SCRIPT],
                         stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                         stderr=err, text=True)
    try:
        line = p.stdout.readline()
        err.seek(0)
        assert line, err.read()
        port = json.loads(line)["port"]

        # reduce side: remote scan of the peer's map output -> final agg
        s = TpuSession({})
        reader = RemoteShuffleReaderExec(("127.0.0.1", port), 777, 4,
                                         schema)
        agg = HashAggregateExec(
            [col("k")], [col("k"), Sum(col("v")).alias("sv"),
                         CountStar().alias("cnt")], reader)
        with ExecCtx(backend="device", conf=s.conf) as ctx:
            rows = []
            from spark_rapids_tpu.exec.core import device_to_host, \
                _rows_from_host
            for pid in range(agg.num_partitions(ctx)):
                for b in agg.partition_iter(ctx, pid):
                    rows.extend(_rows_from_host(device_to_host(b)))

        # oracle: same data + plan in ONE process
        import numpy as np
        rng = np.random.default_rng(3)
        want = TpuSession({}).from_pydict(
            {"k": rng.integers(0, 13, 500).astype(np.int32),
             "v": rng.integers(0, 1000, 500).astype(np.int64)},
            schema, partitions=3) \
            .where(col("v") >= 100).group_by("k") \
            .agg(Sum(col("v")).alias("sv"), CountStar().alias("cnt")) \
            .collect()
        assert sorted(rows) == sorted(want) and len(rows) == 13
    finally:
        try:
            p.stdin.close()
        except OSError:
            pass
        p.wait(timeout=30)

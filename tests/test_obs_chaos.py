"""Observability under chaos: ONE trace tells the whole failure story.

Acceptance-level companion to test_recovery_chaos.py: a TPC-H query run
under a peer-death + spill-corruption storm with tracing enabled must
produce a single exported trace in which the original map stage, the
reduce-side fetches, and the lineage recompute all share one
query_id/trace_id — and EXPLAIN ANALYZE must show the nonzero
spill/recovery metrics on the affected exchange node, not just global
counters.  The bench runner's JSON report carries the same story
(registry counter movement + analyzed plan) for offline runs.
"""
import json
import os

import pytest

from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.obs.registry import get_registry

_STORM = ("shuffle.peer.dead:dead,times=2;"
          "spill.disk.corrupt:corrupt,priority=0,times=2")


def _chaos_conf(trace_dir: str) -> dict:
    return {
        "spark.rapids.test.faults": _STORM,
        # tiny budgets: shuffle outputs spill to disk so the corrupt
        # read-back path actually runs (same as test_recovery_chaos)
        "spark.rapids.memory.tpu.spillStoreSize": 1 << 16,
        "spark.rapids.memory.host.spillStorageSize": 4096,
        "spark.rapids.obs.trace.enabled": "true",
        "spark.rapids.obs.trace.dir": trace_dir,
    }


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_obs_chaos") / "sf001")
    generate_tpch(d, sf=0.01)
    _split_tables(d, ("lineitem", "orders", "customer"), parts=4)
    return d


def _split_tables(data_dir: str, tables, parts: int) -> None:
    import pyarrow.parquet as pq
    for table in tables:
        path = os.path.join(data_dir, table, "part-0.parquet")
        t = pq.read_table(path)
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(data_dir, table,
                                        f"part-{i}.parquet"))


def test_chaos_run_single_trace_and_annotated_plan(data_dir, tmp_path):
    trace_dir = str(tmp_path / "traces")
    before = get_registry().snapshot()
    r = run_benchmark(data_dir, 0.01, ["q3"], verify=True,
                      generate=False, suite="tpch",
                      session_conf=_chaos_conf(trace_dir))[0]
    assert "error" not in r, r
    assert r["ok"], r

    # --- the storm actually fired and was recovered -------------------
    cat = r["metrics"].get("BufferCatalog", {})
    assert cat.get("stage_recomputes", 0) > 0, cat
    d = get_registry().delta(before)["counters"]
    assert d.get("faults.injected", 0) > 0, d

    # --- one trace, one query/trace id across the whole story ---------
    # (verify also runs a host pass; pick the trace holding the chaos)
    traces = [json.load(open(os.path.join(trace_dir, f)))
              for f in os.listdir(trace_dir) if f.startswith("trace_")]
    assert traces
    chaos = [t for t in traces
             if any(e["name"] == "stage.recovery"
                    for e in t["traceEvents"])]
    assert len(chaos) >= 1, "no trace captured the recovery"
    evs = chaos[-1]["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"query", "stage.map", "shuffle.fetch",
            "stage.recovery"} <= names, names
    assert len({e["args"]["query_id"] for e in evs}) == 1
    assert len({e["args"]["trace_id"] for e in evs}) == 1
    # the exported file is named for the same query the events carry
    qid = evs[0]["args"]["query_id"]
    assert chaos[-1]["otherData"]["query_id"] == qid

    # recomputed map writes hang off the recovery span, not stage.map
    rec_ids = {e["args"]["span_id"] for e in evs
               if e["name"] == "stage.recovery"}
    writes = [e for e in evs if e["name"] == "shuffle.map_write"]
    if writes:  # tiny-input coalescing may skip per-piece writes
        assert any(e["args"]["parent_id"] in rec_ids for e in writes)

    # --- EXPLAIN ANALYZE shows recovery on the affected node ----------
    plan_txt = "\n".join(r["observability"]["plan_analyzed"])
    assert "stageRecoveries=" in plan_txt, plan_txt
    line = next(ln for ln in r["observability"]["plan_analyzed"]
                if "stageRecoveries=" in ln)
    assert "ShuffleExchangeExec" in line, line

    # --- bench report carries the full observability record -----------
    obs = r["observability"]
    assert obs["query_id"] and obs["trace_id"]
    assert obs["registry"]["counters"].get("faults.injected", 0) > 0
    # report ids match an exported trace
    assert any(t["otherData"]["query_id"] == obs["query_id"]
               for t in traces)


def test_failed_chaos_query_emits_bundle(data_dir, tmp_path):
    """When the storm outlasts the recovery budget the run fails AND
    leaves a diagnostic bundle naming the exhaustion."""
    diag_dir = str(tmp_path / "diag")
    conf = _chaos_conf(str(tmp_path / "traces"))
    conf["spark.rapids.test.faults"] = "shuffle.peer.dead:dead,times=0"
    conf["spark.rapids.shuffle.recovery.maxStageAttempts"] = "1"
    conf["spark.rapids.obs.diagnostics.dir"] = diag_dir
    r = run_benchmark(data_dir, 0.01, ["q3"], verify=False,
                      generate=False, suite="tpch",
                      session_conf=conf)[0]
    assert not r["ok"]
    assert "StageRecoveryExhausted" in r["error"], r["error"]
    bundles = os.listdir(diag_dir)
    assert len(bundles) == 1, bundles
    doc = json.load(open(os.path.join(diag_dir, bundles[0])))
    assert doc["error"]["type"] == "StageRecoveryExhausted"
    assert doc["span_events"]
    assert doc["faults"]["fired"]
    assert any("ShuffleExchangeExec" in ln for ln in doc["plan_analyzed"])

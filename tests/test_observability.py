"""Unified observability plane: tracer, metrics registry, EXPLAIN
ANALYZE, and failure diagnostics (spark_rapids_tpu/obs/).

Covers the satellite guarantees, not just happy paths:

* device-side ``numOutputRows`` is recorded exactly when a host-side
  count is already known (``ColumnBatch.known_rows``) and NEVER forces
  a D2H sync;
* repeated ``partition_iter_slice`` windows (the adaptive reader's
  re-reads) do not inflate operator metrics;
* OOM split-and-retry pieces carry exact host-side counts, so split
  outputs never double-count rows;
* stage recovery attributes recomputed map outputs to the recovery
  span and the affected exchange NODE, visible in EXPLAIN ANALYZE;
* a failed query emits a bounded diagnostic bundle;
* shuffle counters (retry ladder, circuit breaker, checksum failures)
  and fault injections land in the process metrics registry.

The import-discipline guarantee (obs.trace/obs.diag never imported on
the disabled path) is enforced by ci/premerge.sh in a FRESH interpreter
— it cannot be asserted here because these tests enable tracing.
"""
import json
import os
import sys
import threading
import time

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.obs.registry import (MetricsRegistry, get_registry,
                                           query_metrics_snapshot)
from spark_rapids_tpu.obs.trace import Tracer, new_query_id

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
])
DATA = {"k": [i % 7 for i in range(400)], "v": list(range(400))}


def _session(extra=None):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession(dict(extra or {}))


def _agg_df(s):
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.core import col
    return s.from_pydict(DATA, SCHEMA, partitions=4) \
        .group_by("k").agg(Sum(col("v")))


def _run_device(df, conf):
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    ov, meta = df._overridden(quiet=True)
    with ExecCtx(backend="device", conf=conf) as ctx:
        rows = []
        for b in meta.exec_node.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        return sorted(rows), ctx, meta.exec_node


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_parent_ids():
    tr = Tracer(query_id="q1")
    with tr.span("query", "query") as root:
        with tr.span("stage", "stage") as st:
            tr.event("mark", "stage", detail="x")
        with tr.span("other", "stage"):
            pass
    evs = tr.events_snapshot()
    by_name = {e["name"]: e for e in evs}
    assert by_name["query"]["ph"] == "X"
    assert by_name["stage"]["args"]["parent_id"] == root.span_id
    assert by_name["mark"]["ph"] == "i"
    assert by_name["mark"]["args"]["parent_id"] == st.span_id
    assert all(e["args"]["query_id"] == "q1" for e in evs)
    assert all(e["args"]["trace_id"] == tr.trace_id for e in evs)


def test_out_of_order_span_close():
    """Suspended generators close spans out of LIFO order; the tracer
    must pop by identity, not by stack position."""
    tr = Tracer(query_id="q")

    def gen(name):
        with tr.span(name, "operator"):
            yield 1
            yield 2

    a, b = gen("a"), gen("b")
    next(a)
    next(b)          # stack now [a, b]
    a.close()        # closes a FIRST (out of order)
    b.close()
    names = [e["name"] for e in tr.events_snapshot()]
    assert sorted(names) == ["a", "b"]
    # a fresh span still parents correctly (stack not corrupted)
    with tr.span("c", "operator"):
        tr.event("inner", "operator")
    evs = {e["name"]: e for e in tr.events_snapshot()}
    assert evs["inner"]["args"]["parent_id"] == evs["c"]["args"]["span_id"]


def test_bounded_events_and_drop_count(tmp_path):
    tr = Tracer(query_id="q", max_events=8)
    for i in range(20):
        tr.event(f"e{i}", "query")
    evs = tr.events_snapshot()
    assert len(evs) == 8
    assert [e["name"] for e in evs] == [f"e{i}" for i in range(12, 20)]
    doc = json.load(open(tr.export(str(tmp_path / "t.json"))))
    assert doc["otherData"]["events_dropped"] == 12


def test_export_chrome_trace_format(tmp_path):
    tr = Tracer(query_id="q2")
    with tr.span("query", "query", root="X"):
        tr.event("i1", "shuffle")
    path = str(tmp_path / "t.json")
    tr.export(path)
    doc = json.load(open(path))
    assert set(doc) >= {"traceEvents", "displayTimeUnit", "otherData"}
    for e in doc["traceEvents"]:
        assert {"name", "cat", "ph", "ts", "pid", "tid", "args"} <= set(e)
        if e["ph"] == "X":
            assert "dur" in e and e["dur"] >= 0
    assert doc["otherData"]["query_id"] == "q2"


def test_trace_header_carries_current_span():
    tr = Tracer(query_id="q3")
    assert tr.trace_header() == {"query_id": "q3",
                                 "trace_id": tr.trace_id}
    with tr.span("s", "query") as sp:
        h = tr.trace_header()
        assert h["span_id"] == sp.span_id
        assert h["query_id"] == "q3"


def test_new_query_ids_unique():
    ids = {new_query_id() for _ in range(64)}
    assert len(ids) == 64


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_registry_counters_gauges_delta():
    r = MetricsRegistry()
    r.inc("a")
    r.inc("a", 2)
    r.set_gauge("g", 7.5)
    before = r.snapshot()
    assert before["counters"]["a"] == 3
    assert before["gauges"]["g"] == 7.5
    r.inc("a", 10)
    r.inc("b")
    d = r.delta(before)
    assert d["counters"] == {"a": 10, "b": 1}


def test_registry_object_source_weakref():
    r = MetricsRegistry()

    class Holder:
        def __init__(self):
            self.metrics = {"x": 1, "skip": "str"}

    h = Holder()
    r.register_object_source("h", h)
    snap = r.snapshot()["gauges"]
    assert snap["h.x"] == 1
    assert "h.skip" not in snap          # non-numeric values dropped
    del h
    import gc
    gc.collect()
    assert "h.x" not in r.snapshot()["gauges"]  # weakref: no leak


def test_registry_source_errors_skipped():
    r = MetricsRegistry()
    r.register_source("bad", lambda: 1 / 0)
    r.register_source("good", lambda: {"v": 2})
    snap = r.snapshot()["gauges"]
    assert snap["good.v"] == 2


def test_prometheus_exposition_sanitized():
    r = MetricsRegistry()
    r.inc("shuffle.peer.127.0.0.1:9999.bytes", 5)
    r.set_gauge("g-x", 1)
    text = r.to_prometheus()
    assert "# TYPE" in text
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name = line.split()[0].split("{", 1)[0]
        assert all(c.isalnum() or c == "_" for c in name), line
    # the peer address lands in a label, not the metric name
    assert 'srt_shuffle_peer_bytes{peer="127.0.0.1:9999"} 5' in text


def test_prometheus_tenant_and_fault_labels():
    r = MetricsRegistry()
    r.inc("admission.tenant.alpha.admitted", 3)
    r.inc("admission.tenant.beta.rejected", 1)
    r.inc("faults.injected.cluster.rpc.drop", 2)
    r.inc("faults.injected", 2)
    text = r.to_prometheus()
    assert 'srt_admission_tenant_admitted{tenant="alpha"} 3' in text
    assert 'srt_admission_tenant_rejected{tenant="beta"} 1' in text
    assert 'srt_faults_injected{point="cluster.rpc.drop"} 2' in text
    # the plain aggregate coexists in the same family under ONE TYPE line
    assert text.count("# TYPE srt_faults_injected counter") == 1


def test_breaker_gauges_exported():
    from spark_rapids_tpu.shuffle.retry import (_breaker,
                                                reset_circuit_breakers)
    reset_circuit_breakers()
    before = get_registry().snapshot()["counters"].get(
        "shuffle.breaker.opens", 0)
    b = _breaker(("obs-test-host", 1234))
    try:
        for _ in range(3):
            b.record_failure(RuntimeError("x"), threshold=3)
        gauges = get_registry().snapshot()["gauges"]
        assert gauges["shuffle.breaker.obs-test-host:1234.open"] == 1
        assert gauges["shuffle.breaker.obs-test-host:1234.failures"] == 3
        after = get_registry().snapshot()["counters"]["shuffle.breaker.opens"]
        assert after == before + 1
        # half-open probe failure re-arms WITHOUT recounting an open
        b.record_failure(RuntimeError("y"), threshold=3)
        assert get_registry().snapshot()["counters"][
            "shuffle.breaker.opens"] == before + 1
    finally:
        reset_circuit_breakers()


def test_faults_injected_counter():
    from spark_rapids_tpu.faults import FaultRegistry
    before = get_registry().snapshot()["counters"].get("faults.injected", 0)
    fr = FaultRegistry("store.fetch:error", seed=0)
    assert fr.check("store.fetch", shuffle=1, part=0) is not None
    counters = get_registry().snapshot()["counters"]
    assert counters["faults.injected"] == before + 1
    assert counters.get("faults.injected.store.fetch", 0) >= 1


# ---------------------------------------------------------------------------
# device numOutputRows via known_rows (no D2H sync)
# ---------------------------------------------------------------------------

def test_split_half_preserves_known_rows():
    """Split pieces carry exact host-side counts WITHOUT a device sync
    — downstream metrics count each row exactly once."""
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.memory import split_half
    b = HostBatch.from_pydict(
        {"k": list(range(101)), "v": list(range(101))}, SCHEMA).to_device()
    lo, hi = split_half(b)
    assert lo.known_rows == 51 and hi.known_rows == 50      # no sync needed
    assert lo.host_num_rows() == 51 and hi.host_num_rows() == 50


def test_with_columns_propagates_known_rows():
    from spark_rapids_tpu.host.batch import HostBatch
    b = HostBatch.from_pydict(
        {"k": [1, 2], "v": [3, 4]}, SCHEMA).to_device()
    b.known_rows = 2
    assert b.with_columns(list(b.columns), b.schema).known_rows == 2


def test_oom_split_storm_no_double_count():
    """Under a persistent simulated OOM, every emitted piece is a split
    product; the host-side counts must sum to EXACTLY the input rows."""
    from spark_rapids_tpu.faults import FaultRegistry
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.memory import BufferCatalog, with_retry
    cat = BufferCatalog(device_limit=10 << 20, host_limit=1 << 24)
    cat.faults = FaultRegistry("memory.oom.until_rows:oom,until_rows=20",
                               seed=0)
    b = HostBatch.from_pydict(
        {"k": list(range(100)), "v": list(range(100))}, SCHEMA).to_device()
    out = with_retry(lambda x: x, cat, b, op="ident", min_split_rows=4)
    assert cat.metrics["oom_splits"] > 0
    assert all(p.known_rows is not None for p in out)
    assert sum(p.known_rows for p in out) == 100
    cat.close()


def test_device_num_output_rows_from_known_rows():
    """A device pipeline whose batches carry known_rows records exact
    numOutputRows on those operators; operators whose counts would
    require a sync record none (never a wrong value)."""
    rows, ctx, plan = _run_device(_agg_df(_session()), _session().conf)
    scans = {k: m for k, m in ctx.metrics.items()
             if k.startswith("LocalScanExec")}
    assert scans
    total = sum(m.values.get("numOutputRows", 0) for m in scans.values())
    assert total == len(DATA["k"])


# ---------------------------------------------------------------------------
# partition_iter_slice windows must not inflate metrics
# ---------------------------------------------------------------------------

def test_slice_windows_do_not_inflate_metrics():
    from spark_rapids_tpu.exec import (ExecCtx, HashPartitioning,
                                       LocalScanExec, ShuffleExchangeExec)
    from spark_rapids_tpu.expr.core import col
    scan = LocalScanExec.from_pydict(DATA, SCHEMA, partitions=2,
                                     rows_per_batch=64)
    ex = ShuffleExchangeExec(HashPartitioning([col("k")], 4), scan)
    conf = TpuConf({"spark.sql.adaptive.advisoryPartitionSizeInBytes": 0})
    with ExecCtx(backend="device", conf=conf) as ctx:
        # read every partition through THREE overlapping slice windows
        for _ in range(3):
            for pid in range(4):
                list(ex.partition_iter_slice(ctx, pid, 0, None))
        key = next(k for k in ctx.metrics if k.startswith("LocalScanExec"))
        m = ctx.metrics[key].values
        # the map side materialized ONCE; re-windows hit the transport,
        # never the child
        assert m["numOutputRows"] == len(DATA["k"])
        # the exchange's own instrumented iter never ran (slices use the
        # uninstrumented impl), so no exchange metrics were inflated
        assert not any(k.startswith("ShuffleExchangeExec")
                       and ctx.metrics[k].values.get("numOutputBatches")
                       for k in ctx.metrics)


# ---------------------------------------------------------------------------
# ExecCtx wiring: ids, tracer lifecycle, export
# ---------------------------------------------------------------------------

def test_ctx_ids_stable_and_tracer_disabled_by_default():
    from spark_rapids_tpu.exec.core import ExecCtx
    with ExecCtx(backend="host", conf=TpuConf({})) as ctx:
        assert ctx.query_id == ctx.query_id
        assert ctx.trace_id == ctx.query_id
        assert ctx.tracer is None
        import contextlib
        assert isinstance(ctx.trace_span("x"), contextlib.nullcontext)


def test_ctx_trace_export_on_close(tmp_path):
    from spark_rapids_tpu.exec.core import ExecCtx
    conf = TpuConf({"spark.rapids.obs.trace.enabled": "true",
                    "spark.rapids.obs.trace.dir": str(tmp_path)})
    with ExecCtx(backend="host", conf=conf) as ctx:
        with ctx.trace_span("query", "query"):
            ctx.trace_event("mark", "query")
        qid = ctx.query_id
    files = list(tmp_path.glob("trace_*.json"))
    assert len(files) == 1 and qid in files[0].name
    doc = json.load(open(files[0]))
    assert {e["name"] for e in doc["traceEvents"]} == {"query", "mark"}


def test_query_execution_traced_end_to_end(tmp_path):
    """One device query -> one trace whose every event carries the
    SAME query_id/trace_id, with query/partition/operator/stage spans."""
    conf = TpuConf({"spark.rapids.obs.trace.enabled": "true",
                    "spark.rapids.obs.trace.dir": str(tmp_path)})
    rows, ctx, plan = _run_device(_agg_df(_session()), conf)
    files = list(tmp_path.glob("trace_*.json"))
    assert len(files) == 1
    evs = json.load(open(files[0]))["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"query", "partition", "stage.map", "shuffle.fetch"} <= names
    assert len({e["args"]["query_id"] for e in evs}) == 1
    assert len({e["args"]["trace_id"] for e in evs}) == 1
    # top-level partition spans parent onto the query root even when
    # drained from worker threads (map-side drains parent onto their
    # stage.map span instead)
    root = next(e for e in evs if e["name"] == "query")
    parts = [e for e in evs if e["name"] == "partition"]
    assert parts
    assert any(e["args"]["parent_id"] == root["args"]["span_id"]
               for e in parts)
    span_ids = {e["args"]["span_id"] for e in evs}
    assert all(e["args"]["parent_id"] in span_ids for e in parts)


# ---------------------------------------------------------------------------
# stage recovery: span + node attribution
# ---------------------------------------------------------------------------

_RECOVERY_CONF = {
    "spark.rapids.test.faults": "shuffle.peer.dead:dead,times=1",
    # pin map-side coalescing OFF so per-piece map_write events exist
    "spark.sql.adaptive.advisoryPartitionSizeInBytes": "0",
    "spark.rapids.obs.trace.enabled": "true",
}


def test_recovery_span_owns_recomputed_writes():
    """Recomputed map outputs are attributed to the stage.recovery span,
    NOT the original stage.map span — and both live in ONE trace."""
    s = _session(_RECOVERY_CONF)
    rows, ctx, plan = _run_device(_agg_df(s), s.conf)
    s0 = _session()
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = _agg_df(s0)._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s0.conf))
    evs = ctx.cache["tracer"].events_snapshot()
    assert len({e["args"]["query_id"] for e in evs}) == 1
    rec = [e for e in evs if e["name"] == "stage.recovery"]
    assert rec and rec[0]["args"]["recomputed"] >= 1
    maps = [e for e in evs if e["name"] == "stage.map"]
    assert maps
    writes = [e for e in evs if e["name"] == "shuffle.map_write"]
    rec_ids = {e["args"]["span_id"] for e in rec}
    map_ids = {e["args"]["span_id"] for e in maps}
    recovered = [e for e in writes if e["args"]["parent_id"] in rec_ids]
    original = [e for e in writes if e["args"]["parent_id"] not in rec_ids]
    assert recovered, "no write attributed to the recovery span"
    assert original, "no write attributed to the original map stage"
    assert all(e["args"]["parent_id"] not in map_ids for e in recovered)


def test_recovery_metrics_on_exchange_node():
    s = _session(_RECOVERY_CONF)
    rows, ctx, plan = _run_device(_agg_df(s), s.conf)
    ex = {k: m.values for k, m in ctx.metrics.items()
          if k.startswith("ShuffleExchangeExec")}
    assert any(v.get("stageRecoveries", 0) >= 1 for v in ex.values()), ex
    assert any(v.get("mapOutputsRecomputed", 0) >= 1 for v in ex.values())
    assert any(v.get("recoveryTime", 0) > 0 for v in ex.values())


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

def test_explain_analyze_renders_runtime_metrics():
    s = _session()
    txt = _agg_df(s).explain_analyze()
    assert "HashAggregateExec" in txt and "ShuffleExchangeExec" in txt
    assert "totalTime=" in txt and "numOutputRows=" in txt
    assert "query_id=" in txt and "trace_id=" in txt
    assert "catalog:" in txt


def test_explain_analyze_shows_recovery_on_affected_node():
    s = _session(_RECOVERY_CONF)
    rows, ctx, plan = _run_device(_agg_df(s), s.conf)
    from spark_rapids_tpu.plan.overrides import explain_analyze
    txt = explain_analyze(plan, ctx)
    line = next(ln for ln in txt.splitlines()
                if "ShuffleExchangeExec" in ln and "stageRecoveries" in ln)
    assert "stageRecoveries=1" in line or "stageRecoveries=" in line
    assert "mapOutputsRecomputed=" in line


def test_query_metrics_snapshot_shape():
    s = _session()
    rows, ctx, plan = _run_device(_agg_df(s), s.conf)
    snap = query_metrics_snapshot(ctx)
    assert "operators" in snap and "registry" in snap
    assert any(k.startswith("LocalScanExec") for k in snap["operators"])
    assert {"counters", "gauges"} <= set(snap["registry"])


# ---------------------------------------------------------------------------
# failure diagnostics
# ---------------------------------------------------------------------------

def test_diagnostic_bundle_on_forced_failure(tmp_path):
    from spark_rapids_tpu.shuffle.errors import StageRecoveryExhausted
    d = tmp_path / "diag"
    s = _session({
        "spark.rapids.test.faults": "shuffle.peer.dead:dead,times=0",
        "spark.rapids.shuffle.recovery.maxStageAttempts": "1",
        "spark.rapids.obs.trace.enabled": "true",
        "spark.rapids.obs.diagnostics.dir": str(d),
    })
    with pytest.raises(StageRecoveryExhausted):
        _run_device(_agg_df(s), s.conf)
    bundles = list(d.glob("diag_*.json"))
    assert len(bundles) == 1
    doc = json.load(open(bundles[0]))
    assert doc["kind"] == "spark_rapids_tpu.diagnostic_bundle"
    assert doc["error"]["type"] == "StageRecoveryExhausted"
    assert doc["query_id"] and doc["trace_id"]
    assert isinstance(doc["plan_analyzed"], list) and doc["plan_analyzed"]
    assert any("ShuffleExchangeExec" in ln for ln in doc["plan_analyzed"])
    assert doc["span_events"], "span events missing from bundle"
    assert doc["faults"]["spec"].startswith("shuffle.peer.dead")
    assert doc["faults"]["fired"], "fault audit log missing"
    assert "tier_occupancy" in doc["catalog"]
    assert any(k.startswith("spark.rapids") for k in doc["conf"])
    assert doc["metrics"]["operators"]
    # the bundle validates against the checked-in CI schema
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "scripts"))
    try:
        from validate_obs import load_schema, validate
        assert validate(doc, load_schema("bundle")) == []
    finally:
        sys.path.pop(0)


def test_no_bundle_when_dir_unset(tmp_path):
    from spark_rapids_tpu.shuffle.errors import StageRecoveryExhausted
    s = _session({
        "spark.rapids.test.faults": "shuffle.peer.dead:dead,times=0",
        "spark.rapids.shuffle.recovery.maxStageAttempts": "1",
    })
    with pytest.raises(StageRecoveryExhausted):
        _run_device(_agg_df(s), s.conf)   # must not raise from diag path


def test_bundle_truncates_error_message(tmp_path):
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.obs.diag import maybe_emit_bundle

    class _Node:
        children = ()

        def node_desc(self):
            return "FakeExec"

    with ExecCtx(backend="host", conf=TpuConf({})) as ctx:
        err = RuntimeError("x" * 20000)
        path = maybe_emit_bundle(ctx, _Node(), err, str(tmp_path))
        assert path is not None
        doc = json.load(open(path))
        assert len(doc["error"]["message"]) <= 4096


# ---------------------------------------------------------------------------
# TCP shuffle: trace propagation + wire counters
# ---------------------------------------------------------------------------

def test_trace_header_crosses_tcp_wire():
    """The serving peer logs the ORIGINATING query's ids: a reduce-side
    fetch from another process lands in the right trace."""
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    conf = TpuConf({"spark.rapids.obs.trace.enabled": "true"})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            hb = HostBatch.from_pydict({"k": [1], "v": [2]}, SCHEMA)
            t.write_partition(1, 0, 0, host_to_device(hb))
            tracer = ctx.tracer
            with tracer.span("reduce", "query") as sp:
                got = list(fetch_remote_with_retry(
                    t.address, 1, 0, conf=conf, tracer=tracer,
                    trace=tracer.trace_header()))
            assert len(got) == 1
            assert t.server_metrics["traced_fetches"] == 1
            logged = t._server.trace_log[-1]
            assert logged["query_id"] == ctx.query_id
            assert logged["trace_id"] == ctx.trace_id
            assert logged["span_id"] == sp.span_id
        finally:
            t.close()


def test_untraced_fetch_interops():
    """No trace header -> old-client interop: served fine, not logged."""
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport, fetch_remote
    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            hb = HostBatch.from_pydict({"k": [1], "v": [2]}, SCHEMA)
            t.write_partition(1, 0, 0, host_to_device(hb))
            got = list(fetch_remote(t.address, 1, 0))
            assert len(got) == 1
            assert t.server_metrics["traced_fetches"] == 0
            assert len(t._server.trace_log) == 0
        finally:
            t.close()


def test_retry_events_and_counters_share_trace():
    """A mid-stream reset: the retry event lands in the SAME trace as
    the query, and ladder counters move in the process registry."""
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.shuffle.retry import (fetch_remote_with_retry,
                                                reset_circuit_breakers)
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    reset_circuit_breakers()
    conf = TpuConf({"spark.rapids.test.faults":
                    "tcp.server.frame:reset,nth=2",
                    "spark.rapids.shuffle.tcp.retryWaitSeconds": "0.02",
                    "spark.rapids.obs.trace.enabled": "true"})
    before = get_registry().snapshot()
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            hb = HostBatch.from_pydict({"k": [1, 2], "v": [3, 4]}, SCHEMA)
            for m in range(3):
                t.write_partition(1, m, 0, host_to_device(hb))
            tracer = ctx.tracer
            got = list(fetch_remote_with_retry(
                t.address, 1, 0, conf=conf,
                tracer=tracer, trace=tracer.trace_header()))
            assert len(got) == 3
            evs = tracer.events_snapshot()
            retries = [e for e in evs if e["name"] == "shuffle.fetch.retry"]
            assert len(retries) == 1
            assert retries[0]["args"]["query_id"] == ctx.query_id
            assert retries[0]["args"]["delivered"] >= 1
        finally:
            t.close()
    d = get_registry().delta(before)["counters"]
    assert d.get("shuffle.fetch.retries", 0) >= 1
    assert d.get("shuffle.fetch.attempts", 0) >= 2
    assert d.get("shuffle.fetch.bytes", 0) > 0
    assert any(k.startswith("shuffle.peer.") and k.endswith(".bytes_fetched")
               for k in d)


def test_checksum_failure_counter():
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.shuffle.tcp import (ShuffleTransportError,
                                              TcpShuffleTransport,
                                              fetch_remote)
    conf = TpuConf({"spark.rapids.test.faults":
                    "tcp.server.frame:corrupt,nth=1"})
    before = get_registry().snapshot()
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            hb = HostBatch.from_pydict({"k": [1], "v": [2]}, SCHEMA)
            t.write_partition(1, 0, 0, host_to_device(hb))
            with pytest.raises(ShuffleTransportError):
                list(fetch_remote(t.address, 1, 0))
        finally:
            t.close()
    d = get_registry().delta(before)["counters"]
    assert d.get("shuffle.fetch.checksum_failures", 0) >= 1

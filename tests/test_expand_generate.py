"""Expand (rollup/cube/grouping sets) + Generate (explode) execs.

Differential device-vs-host tests (reference GpuExpandExec.scala:67,
GpuGenerateExec.scala:101; test style SparkQueryCompareTestSuite).
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.aggregates import Average, CountStar, Sum
from spark_rapids_tpu.expr.core import col, grouping_id
from spark_rapids_tpu.session import TpuSession


def _both(df):
    dev = sorted(df.collect(), key=str)
    ov, meta = df._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, df._s.conf), key=str)
    return dev, host


@pytest.fixture
def sales_df():
    s = TpuSession({})
    rng = np.random.default_rng(7)
    n = 500
    schema = T.Schema([T.StructField("state", T.StringType()),
                       T.StructField("county", T.StringType()),
                       T.StructField("cat", T.IntegerType()),
                       T.StructField("qty", T.IntegerType()),
                       T.StructField("price", T.DoubleType())])
    states = ["CA", "TX", None, "NY"]
    data = {
        "state": [states[i] for i in rng.integers(0, 4, n)],
        "county": [f"c{i}" for i in rng.integers(0, 5, n)],
        "cat": [int(i) for i in rng.integers(0, 3, n)],
        "qty": [int(i) for i in rng.integers(1, 10, n)],
        "price": [round(float(x), 2) for x in rng.uniform(1, 100, n)],
    }
    return s.from_pydict(data, schema, partitions=2, rows_per_batch=128)


def test_rollup_q27_shape(sales_df):
    """q27-shaped rollup: avg over rollup(state, county)."""
    df = sales_df.rollup("state", "county").agg(
        Average(col("qty")).alias("avg_qty"),
        Sum(col("price")).alias("rev"),
        CountStar().alias("cnt"))
    dev, host = _both(df)
    assert len(dev) > 10
    for d, h in zip(dev, host):
        assert d[0] == h[0] and d[1] == h[1] and d[4] == h[4]
        assert d[2] == pytest.approx(h[2], rel=1e-9)
        assert d[3] == pytest.approx(h[3], rel=1e-9)


def test_rollup_data_null_vs_rollup_null(sales_df):
    """state=None data rows must not merge with the rollup total row."""
    df = sales_df.rollup("state").agg(CountStar().alias("cnt"),
                                      grouping_id().alias("gid"))
    dev, host = _both(df)
    assert dev == host
    nulls = [r for r in dev if r[0] is None]
    # one data-null group (gid 0) and one grand total (gid 1)
    assert sorted(r[2] for r in nulls) == [0, 1]
    total = next(r for r in nulls if r[2] == 1)
    assert total[1] == 500


def test_cube(sales_df):
    df = sales_df.cube("state", "cat").agg(Sum(col("qty")).alias("s"))
    dev, host = _both(df)
    assert dev == host
    gids = {r for r in range(4)}
    # cube produces all four grouping-id combinations
    df2 = sales_df.cube("state", "cat").agg(grouping_id().alias("g"))
    dev2, _ = _both(df2)
    assert {r[2] for r in dev2} == gids


def test_grouping_sets_explicit(sales_df):
    df = sales_df.grouping_sets(["state", "cat"], [["state"], ["cat"], []]) \
        .agg(CountStar().alias("cnt"))
    dev, host = _both(df)
    assert dev == host
    # no (state, cat) detail rows: every row has at least one null key side
    df3 = sales_df.grouping_sets(["state", "cat"], [["state"], ["cat"], []]) \
        .agg(grouping_id().alias("g"))
    dev3, _ = _both(df3)
    assert {r[2] for r in dev3} == {1, 2, 3}


def test_explode_split():
    s = TpuSession({})
    schema = T.Schema([T.StructField("id", T.IntegerType()),
                       T.StructField("tags", T.StringType())])
    df = s.from_pydict({"id": [1, 2, 3, 4],
                        "tags": ["a,b,c", "", None, "xy"]}, schema)
    out = df.explode_split("tags", ",", output_name="tag")
    dev, host = _both(out)
    assert dev == host
    assert (1, "a,b,c", "a") in dev and (1, "a,b,c", "c") in dev
    assert (2, "", "") in dev            # split("") -> [""]
    assert not any(r[0] == 3 for r in dev)  # null input -> no rows


def test_posexplode_outer():
    s = TpuSession({})
    schema = T.Schema([T.StructField("id", T.IntegerType()),
                       T.StructField("tags", T.StringType())])
    df = s.from_pydict({"id": [1, 2], "tags": ["a,b", None]}, schema)
    out = df.explode_split("tags", ",", output_name="tag", pos=True,
                           outer=True)
    dev, host = _both(out)
    assert dev == host
    assert (1, "a,b", 0, "a") in dev and (1, "a,b", 1, "b") in dev
    assert (2, None, None, None) in dev  # outer keeps the null row


def test_explode_then_aggregate():
    s = TpuSession({})
    schema = T.Schema([T.StructField("id", T.IntegerType()),
                       T.StructField("tags", T.StringType())])
    df = s.from_pydict(
        {"id": [1, 2, 3], "tags": ["a,b", "b,c,b", "a"]}, schema)
    out = df.explode_split("tags", ",", output_name="tag") \
        .group_by("tag").agg(CountStar().alias("cnt"))
    dev, host = _both(out)
    assert dev == host
    assert ("b", 3) in dev and ("a", 2) in dev and ("c", 1) in dev


def test_rollup_computed_key_shadowing_child_column(sales_df):
    """A computed rollup key aliased to an existing column name must group
    by the expression, not the raw column (round-3 review finding)."""
    from spark_rapids_tpu.expr.core import col as c
    df = sales_df.rollup((c("cat") + c("cat")).alias("cat")) \
        .agg(CountStar().alias("cnt"))
    dev, host = _both(df)
    assert dev == host
    keys = {r[0] for r in dev if r[0] is not None}
    assert keys <= {0, 2, 4}  # doubled categories, not raw 0/1/2

"""Stage recovery units: epoch tagging, attempt budget, terminal
classification, disk-tier integrity, and spill-file lifecycle.

The chaos-level counterpart (TPC-H under peer-death and spill-corruption
storms) lives in test_recovery_chaos.py; these tests pin the individual
mechanisms: a stale write from a superseded map attempt is discarded, an
exhausted attempt budget surfaces StageRecoveryExhausted, terminal
errors bypass the transport retry ladder, a corrupt disk read-back is a
LOSS (recoverable) rather than a crash, and spill files never outlive
their entries.
"""
import glob
import os

import jax.numpy as jnp
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.ops.kernels import DeviceColumn
from spark_rapids_tpu.shuffle.errors import (MapOutputLostError,
                                             ShuffleFetchError,
                                             StageRecoveryExhausted)
from spark_rapids_tpu.shuffle.local import LocalShuffleTransport


def _batch(values):
    data = jnp.asarray(values, jnp.int64)
    col = DeviceColumn(data, jnp.ones(data.shape, jnp.bool_), T.LongType())
    return ColumnBatch([col], len(values), T.Schema(
        [T.StructField("x", T.LongType(), True)]))


def _rows(b):
    import jax
    return [int(v) for v in jax.device_get(b.columns[0].data)[:b.num_rows]]


# ---------------------------------------------------------------------------
# epoch-tagged map outputs (transport level)
# ---------------------------------------------------------------------------

def test_invalidate_then_recompute_roundtrip():
    t = LocalShuffleTransport(TpuConf({}), ctx=None)
    t.write_partition("s", 0, 0, _batch([1, 2]))
    t.write_partition("s", 1, 0, _batch([3, 4]))
    assert t.map_epoch("s", 0) == 0

    new_epochs = t.invalidate_map_outputs("s", [0])
    assert new_epochs == {0: 1}
    assert t.map_epoch("s", 0) == 1
    assert t.metrics["map_outputs_invalidated"] == 1
    with pytest.raises(MapOutputLostError) as ei:
        list(t.fetch_partition("s", 0))
    assert ei.value.lost == {0: 1}
    assert ei.value.terminal

    # the recomputed write refills the SAME slot: fetch order is stable
    t.write_partition("s", 0, 0, _batch([1, 2]), epoch=1)
    out = [_rows(b) for b in t.fetch_partition("s", 0)]
    assert out == [[1, 2], [3, 4]]
    t.close()


def test_stale_write_from_dead_attempt_discarded():
    t = LocalShuffleTransport(TpuConf({}), ctx=None)
    t.write_partition("s", 0, 0, _batch([1]))
    t.invalidate_map_outputs("s", [0])
    # a straggling write still tagged with the superseded epoch must
    # not resurrect the slot
    t.write_partition("s", 0, 0, _batch([9]), epoch=0)
    assert t.metrics["stale_writes_discarded"] == 1
    with pytest.raises(MapOutputLostError):
        list(t.fetch_partition("s", 0))
    t.write_partition("s", 0, 0, _batch([1]), epoch=1)
    assert [_rows(b) for b in t.fetch_partition("s", 0)] == [[1]]
    t.close()


def test_lost_slice_names_every_map_in_range():
    t = LocalShuffleTransport(TpuConf({}), ctx=None)
    for m in range(3):
        t.write_partition("s", m, 0, _batch([m]))
    t.invalidate_map_outputs("s", [0, 2])
    with pytest.raises(MapOutputLostError) as ei:
        list(t.fetch_partition("s", 0))
    assert sorted(ei.value.lost) == [0, 2]
    # a sub-range that skips the lost slots still streams
    assert [_rows(b) for b in t.fetch_partition("s", 0, lo=1, hi=2)] \
        == [[1]]
    t.close()


# ---------------------------------------------------------------------------
# terminal vs transient classification (retry ladder)
# ---------------------------------------------------------------------------

def test_map_output_lost_bypasses_retry_ladder(monkeypatch):
    from spark_rapids_tpu.shuffle import retry as retry_mod
    retry_mod.reset_circuit_breakers()
    calls = []

    def dead_fetch(*a, **k):
        calls.append(1)
        raise MapOutputLostError("s", 0, {0: 0})
        yield  # pragma: no cover

    monkeypatch.setattr(retry_mod, "fetch_remote", dead_fetch)
    with pytest.raises(MapOutputLostError):
        list(retry_mod.fetch_remote_with_retry(
            ("lost-peer", 1), "s", 0, max_retries=5, retry_wait=0.0))
    # terminal: ONE attempt, no reconnects, no breaker wind-up
    assert len(calls) == 1
    assert retry_mod._breaker(("lost-peer", 1)).failures == 0


def test_ladder_exhaustion_is_terminal(monkeypatch):
    from spark_rapids_tpu.shuffle import retry as retry_mod
    retry_mod.reset_circuit_breakers()

    def flaky_fetch(*a, **k):
        raise ShuffleFetchError("connection reset")
        yield  # pragma: no cover

    monkeypatch.setattr(retry_mod, "fetch_remote", flaky_fetch)
    with pytest.raises(ShuffleFetchError) as ei:
        list(retry_mod.fetch_remote_with_retry(
            ("flaky-peer", 1), "s", 0, max_retries=1, retry_wait=0.0))
    assert ei.value.terminal


# ---------------------------------------------------------------------------
# lineage + budget (exec level)
# ---------------------------------------------------------------------------

_SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
])
_DATA = {"k": [i % 13 for i in range(500)], "v": list(range(500))}


def _session(extra=None):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession(dict(extra or {}))


def _agg_df(s, key="k"):
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.core import col
    return s.from_pydict(_DATA, _SCHEMA, partitions=4) \
        .group_by(key).agg(Sum(col("v")))


def _run_device(df, conf):
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    ov, meta = df._overridden(quiet=True)
    with ExecCtx(backend="device", conf=conf) as ctx:
        rows = []
        for b in meta.exec_node.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        metrics = dict(ctx.catalog.metrics)
    return sorted(rows), metrics


def _oracle(df, conf):
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = df._overridden(quiet=True)
    return sorted(collect_host(meta.exec_node, conf))


def test_peer_death_recovered_exact():
    s = _session({"spark.rapids.test.faults":
                  "shuffle.peer.dead:dead,times=1"})
    df = _agg_df(s)
    rows, m = _run_device(df, s.conf)
    s0 = _session()
    assert rows == _oracle(_agg_df(s0), s0.conf)
    assert m["stage_recomputes"] >= 1
    assert m["map_outputs_recomputed"] >= 1
    assert m["recovery_wall_s"] > 0


def test_recovery_disabled_fails_fast_naming_outputs():
    s = _session({"spark.rapids.test.faults":
                  "shuffle.peer.dead:dead,times=1",
                  "spark.rapids.shuffle.recovery.enabled": "false"})
    with pytest.raises(MapOutputLostError) as ei:
        _run_device(_agg_df(s), s.conf)
    assert "map output lost" in str(ei.value)
    assert "map 0" in str(ei.value)


def test_attempt_budget_exhaustion():
    # a persistently dead peer (times=0 -> fires forever) must stop at
    # the per-stage budget, not recompute unboundedly
    s = _session({"spark.rapids.test.faults":
                  "shuffle.peer.dead:dead,times=0",
                  "spark.rapids.shuffle.recovery.maxStageAttempts": "2"})
    with pytest.raises(StageRecoveryExhausted) as ei:
        _run_device(_agg_df(s), s.conf)
    assert "2 recovery attempts" in str(ei.value)
    assert "maxStageAttempts" in str(ei.value)


def test_mid_recovery_observation_does_not_cascade():
    """A reader that catches a slot between invalidation and the
    recovering thread's rewrite observes an EMPTY slot at the very
    epoch the rewrite carries — epoch ordering alone cannot tell that
    apart from a genuine loss.  The presence re-check must classify it
    as already repaired, or each such observation re-invalidates a
    healthy shuffle and the rounds cascade until the budget exhausts."""
    from spark_rapids_tpu.exec.recovery import _recover

    t = LocalShuffleTransport(TpuConf({}), ctx=None)
    t.write_partition("s", 0, 0, _batch([1, 2]))
    new_epochs = t.invalidate_map_outputs("s", [0])
    # mid-window observation: slot empty, already at the new epoch
    with pytest.raises(MapOutputLostError) as ei:
        list(t.fetch_partition("s", 0))
    assert ei.value.observed_empty
    assert ei.value.lost == {0: new_epochs[0]}
    # the concurrent recovery completes its rewrite
    t.write_partition("s", 0, 0, _batch([1, 2]), epoch=new_epochs[0])
    assert t.map_output_present("s", 0, 0)

    class _Ctx:
        # budget 0: any attempt _recover tries to start raises
        # StageRecoveryExhausted, so a clean return proves the
        # presence re-check classified the outputs as repaired
        conf = TpuConf({"spark.rapids.shuffle.recovery"
                        ".maxStageAttempts": "0"})

        def check_cancel(self):
            pass

        def lineage_for(self, sid):
            return object()

        def cached(self, key, factory):
            return factory()

    _recover(_Ctx(), t, ei.value)
    assert t.map_epoch("s", 0) == new_epochs[0]  # NOT re-invalidated
    assert [_rows(b) for b in t.fetch_partition("s", 0)] == [[1, 2]]
    # a loss observed with the data still present (dead peer) is NOT
    # skippable by presence: it must reach the budget check
    dead = MapOutputLostError("s", 0, {0: t.map_epoch("s", 0)},
                              "injected fault: shuffle.peer.dead")
    assert not dead.observed_empty
    with pytest.raises(StageRecoveryExhausted):
        _recover(_Ctx(), t, dead)
    t.close()


def test_conf_fingerprint_drift_rejected():
    from spark_rapids_tpu.exec.recovery import (ShuffleLineage,
                                                conf_fingerprint)

    class _Ex:
        shuffle_id = "s"
        children = []

    class _Ctx:
        conf = TpuConf({"a": "1"})

    lineage = ShuffleLineage(exchange=_Ex(), coalesced=False, num_parts=1,
                             map_src={0: 0},
                             conf_fp=conf_fingerprint(TpuConf({"a": "2"})))
    with pytest.raises(RuntimeError, match="conf changed"):
        lineage.recompute(_Ctx(), None, {0: 1})


# ---------------------------------------------------------------------------
# disk spill tier: CRC sidecars, corruption -> loss, ENOSPC, lifecycle
# ---------------------------------------------------------------------------

def _catalog(tmp_path, faults="", host_limit=4096):
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    conf = TpuConf({"spark.rapids.test.faults": faults} if faults else {})
    return BufferCatalog(host_limit=host_limit, spill_dir=str(tmp_path),
                         conf=conf)


def test_crc_sidecar_written_and_verified(tmp_path):
    from spark_rapids_tpu.memory.catalog import (SpillPriority,
                                                 SpillableColumnarBatch)
    cat = _catalog(tmp_path)
    scb = SpillableColumnarBatch(_batch(list(range(1024))), cat,
                                 SpillPriority.SHUFFLE_OUTPUT)
    cat.spill_device(1 << 30)
    e = cat._entries[scb._id]
    assert e.tier == "disk"
    sidecar = e.disk_path + ".crc"
    assert os.path.exists(sidecar)
    algo, hexval, length = open(sidecar).read().split(":")
    assert algo in ("crc32c", "crc32")
    assert int(length) == os.path.getsize(e.disk_path)
    got = scb.get()
    assert _rows(got) == list(range(1024))
    scb.unpin()
    scb.close()
    cat.close()


def test_corrupt_readback_is_loss_not_crash(tmp_path):
    from spark_rapids_tpu.memory.catalog import (SpillCorruptionError,
                                                 SpillPriority,
                                                 SpillableColumnarBatch)
    cat = _catalog(tmp_path,
                   faults="spill.disk.corrupt:corrupt,priority=0,times=1")
    scb = SpillableColumnarBatch(_batch(list(range(1024))), cat,
                                 SpillPriority.SHUFFLE_OUTPUT)
    cat.spill_device(1 << 30)
    e = cat._entries[scb._id]
    assert e.tier == "disk"
    with pytest.raises(SpillCorruptionError):
        scb.get()
    assert e.tier == "lost"
    assert cat.metrics["spill_crc_failures"] == 1
    # the unverifiable file and its sidecar are gone; a later read of
    # the lost tier keeps failing deterministically
    assert not _spill_files(tmp_path)
    with pytest.raises(SpillCorruptionError):
        scb.get()
    cat.close()


def test_enospc_degrades_into_oom_scope(tmp_path):
    from spark_rapids_tpu.memory.catalog import (SpillPriority,
                                                 SpillableColumnarBatch)
    cat = _catalog(tmp_path,
                   faults="spill.disk.enospc:enospc,times=1")
    scb = SpillableColumnarBatch(_batch(list(range(1024))), cat,
                                 SpillPriority.SHUFFLE_OUTPUT)
    # a full disk must NOT raise out of spill: it returns what it freed
    # so the OOM-retry ladder (split-and-retry) takes over
    freed = cat.spill_device(1 << 30)
    assert freed == 0
    assert cat.metrics["spill_enospc"] == 1
    assert cat._entries[scb._id].tier == "device"
    assert not _spill_files(tmp_path)
    # the batch is still intact and servable from its device tier
    assert _rows(scb.get()) == list(range(1024))
    scb.unpin()
    cat.close()


def _spill_files(tmp_path):
    return [p for p in glob.glob(os.path.join(str(tmp_path), "**", "*"),
                                 recursive=True) if os.path.isfile(p)]


def test_invalidation_deletes_spilled_files(tmp_path):
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.shuffle import make_transport
    # host arena too small for the batch -> the spill goes direct to disk
    conf = TpuConf({"spark.rapids.memory.spill.dir": str(tmp_path),
                    "spark.rapids.memory.host.spillStorageSize": 4096})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = make_transport(conf, ctx)
        t.write_partition("s", 0, 0, _batch(list(range(1024))))
        ctx.catalog.spill_device(1 << 30)
        assert _spill_files(tmp_path)
        t.invalidate_map_outputs("s", [0])
        assert not _spill_files(tmp_path)
        t.close()


def test_spill_dir_clean_after_ctx_close(tmp_path):
    """Leak check: nothing in the spill dir survives ExecCtx close, even
    with outputs spilled to disk mid-query."""
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.shuffle import make_transport
    conf = TpuConf({"spark.rapids.memory.spill.dir": str(tmp_path),
                    "spark.rapids.memory.host.spillStorageSize": 4096})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = make_transport(conf, ctx)
        for m in range(4):
            t.write_partition("s", m, 0, _batch(list(range(1024))))
        ctx.catalog.spill_device(1 << 30)
        assert _spill_files(tmp_path)
        t.close()
    assert not _spill_files(tmp_path)


# ---------------------------------------------------------------------------
# mesh path: lost device slice -> single-device recompute
# ---------------------------------------------------------------------------

def test_mesh_slice_lost_falls_back_single_device():
    from spark_rapids_tpu.exec.mesh_exec import MeshAggregateExec

    s = _session({"spark.rapids.tpu.mesh.deviceCount": 8,
                  "spark.rapids.test.faults":
                  "mesh.slice.lost:lost,op=meshagg,times=1"})
    df = _agg_df(s)
    ov, meta = df._overridden(quiet=True)
    assert any(isinstance(n, MeshAggregateExec)
               for n in _walk(meta.exec_node)), \
        "plan must lower to the mesh for this test to mean anything"
    rows, m = _run_device(df, s.conf)
    s0 = _session()
    assert rows == _oracle(_agg_df(s0), s0.conf)
    # the lost slice was recovered by the single-device recompute
    assert m["stage_recomputes"] >= 1
    assert m["recovery_wall_s"] > 0


def _walk(node):
    yield node
    for c in getattr(node, "children", []):
        yield from _walk(c)

"""Join differential tests: device sort-merge kernel vs CPU oracle.

Mirrors the reference's join coverage (integration_tests join_test.py:
all join types x key types x nulls; tests/GpuHashJoinSuite) with fuzzed
key data including nulls, NaN, -0.0 and duplicate keys.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import (CrossJoinExec, JoinExec, LocalScanExec,
                                   collect_device, collect_host)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

L_SCHEMA = T.Schema([
    T.StructField("lk", T.IntegerType(), True),
    T.StructField("lv", T.LongType(), True),
    T.StructField("ls", T.StringType(), True),
])
R_SCHEMA = T.Schema([
    T.StructField("rk", T.IntegerType(), True),
    T.StructField("rv", T.DoubleType(), True),
])


def _sides(rng, nl=120, nr=90, key_range=25):
    lk = [None if rng.random() < 0.08 else int(x)
          for x in rng.integers(0, key_range, nl)]
    rk = [None if rng.random() < 0.08 else int(x)
          for x in rng.integers(0, key_range, nr)]
    left = LocalScanExec.from_pydict({
        "lk": lk,
        "lv": [int(x) for x in rng.integers(-50, 50, nl)],
        "ls": [f"s{x}" if x % 4 else None for x in rng.integers(0, 30, nl)],
    }, L_SCHEMA, rows_per_batch=37)
    right = LocalScanExec.from_pydict({
        "rk": rk,
        "rv": [None if rng.random() < 0.1 else float(np.round(x, 2))
               for x in rng.normal(size=nr)],
    }, R_SCHEMA, rows_per_batch=41)
    return left, right


@pytest.mark.parametrize("jt", ["inner", "left", "right", "full", "semi",
                                "anti"])
def test_join_types_match_oracle(rng, jt):
    left, right = _sides(rng)
    plan = JoinExec(left, right, [col("lk")], [col("rk")], jt)
    rows = assert_tpu_and_cpu_equal(plan)
    assert rows  # non-degenerate


def test_inner_join_row_semantics(rng):
    left = LocalScanExec.from_pydict(
        {"lk": [1, 2, 2, None], "lv": [10, 20, 21, 30],
         "ls": ["a", "b", "c", "d"]}, L_SCHEMA)
    right = LocalScanExec.from_pydict(
        {"rk": [2, 2, 3, None], "rv": [0.5, 0.6, 0.7, 0.8]}, R_SCHEMA)
    plan = JoinExec(left, right, [col("lk")], [col("rk")], "inner")
    rows = sorted(collect_host(plan))
    # 2x2 match for key 2; nulls never match
    assert len(rows) == 4
    assert all(r[0] == 2 for r in rows)
    assert sorted(collect_device(plan)) == rows


def test_left_join_keeps_null_keys(rng):
    left = LocalScanExec.from_pydict(
        {"lk": [None, 5], "lv": [1, 2], "ls": ["x", "y"]}, L_SCHEMA)
    right = LocalScanExec.from_pydict(
        {"rk": [7], "rv": [1.0]}, R_SCHEMA)
    plan = JoinExec(left, right, [col("lk")], [col("rk")], "left")
    rows = sorted(collect_host(plan), key=lambda r: str(r))
    assert len(rows) == 2
    assert all(r[3] is None and r[4] is None for r in rows)
    assert_tpu_and_cpu_equal(plan)


def test_full_join_unmatched_both_sides(rng):
    left, right = _sides(rng, nl=60, nr=60, key_range=40)
    plan = JoinExec(left, right, [col("lk")], [col("rk")], "full")
    cpu = assert_tpu_and_cpu_equal(plan)
    # full join row count >= max side count
    assert len(cpu) >= 60


def test_join_on_expression_keys(rng):
    left, right = _sides(rng)
    plan = JoinExec(left, right, [Cast(col("lk"), T.LongType())],
                    [Cast(col("rk"), T.LongType())], "inner")
    assert_tpu_and_cpu_equal(plan)


def test_join_multi_key_with_strings(rng):
    schema_a = T.Schema([T.StructField("k1", T.IntegerType(), True),
                         T.StructField("s1", T.StringType(), True)])
    schema_b = T.Schema([T.StructField("k2", T.IntegerType(), True),
                         T.StructField("s2", T.StringType(), True)])
    n = 80
    a = LocalScanExec.from_pydict({
        "k1": [int(x) for x in rng.integers(0, 5, n)],
        "s1": [f"g{x}" for x in rng.integers(0, 4, n)]}, schema_a)
    b = LocalScanExec.from_pydict({
        "k2": [int(x) for x in rng.integers(0, 5, n)],
        "s2": [f"g{x}" for x in rng.integers(0, 4, n)]}, schema_b)
    plan = JoinExec(a, b, [col("k1"), col("s1")], [col("k2"), col("s2")],
                    "inner")
    rows = assert_tpu_and_cpu_equal(plan)
    for r in rows:
        assert r[0] == r[2] and r[1] == r[3]


def test_join_nan_and_negzero_keys(rng):
    sa = T.Schema([T.StructField("k", T.DoubleType(), True)])
    sb = T.Schema([T.StructField("k2", T.DoubleType(), True)])
    a = LocalScanExec.from_pydict(
        {"k": [float("nan"), -0.0, 1.5, None]}, sa)
    b = LocalScanExec.from_pydict(
        {"k2": [float("nan"), 0.0, 2.5, None]}, sb)
    plan = JoinExec(a, b, [col("k")], [col("k2")], "inner")
    rows = collect_host(plan)
    # NaN==NaN and -0.0==0.0; nulls never match
    assert len(rows) == 2
    assert_tpu_and_cpu_equal(plan)


def test_inner_join_with_condition(rng):
    left, right = _sides(rng)
    plan = JoinExec(left, right, [col("lk")], [col("rk")], "inner",
                    condition=col("lv") > lit(0))
    cpu = assert_tpu_and_cpu_equal(plan)
    assert all(r[1] > 0 for r in cpu)


def test_cross_join_with_condition(rng):
    left, right = _sides(rng, nl=20, nr=15)
    plan = CrossJoinExec(left, right)
    cpu = assert_tpu_and_cpu_equal(plan)
    assert len(cpu) == 20 * 15
    plan2 = CrossJoinExec(left, right, condition=col("lv") > col("rv"))
    assert_tpu_and_cpu_equal(plan2)


def test_join_empty_sides(rng):
    left = LocalScanExec.from_pydict(
        {"lk": [], "lv": [], "ls": []}, L_SCHEMA)
    right = LocalScanExec.from_pydict(
        {"rk": [1, 2], "rv": [0.1, 0.2]}, R_SCHEMA)
    for jt in ("inner", "left", "full", "semi", "anti", "right"):
        plan = JoinExec(left, right, [col("lk")], [col("rk")], jt)
        assert_tpu_and_cpu_equal(plan)


def test_condition_rejected_for_outer():
    left = LocalScanExec.from_pydict(
        {"lk": [1], "lv": [1], "ls": ["a"]}, L_SCHEMA)
    right = LocalScanExec.from_pydict({"rk": [1], "rv": [1.0]}, R_SCHEMA)
    with pytest.raises(ValueError):
        JoinExec(left, right, [col("lk")], [col("rk")], "left",
                 condition=col("lv") > lit(0))


def test_session_right_join_asymmetric_schemas():
    """Session-level right join with different schemas per side
    (regression: the planner's rewrite passes reassigned exec children
    in meta order, clobbering JoinExec's internal side swap — columns
    came back misaligned and rows were a left join's)."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.exec.core import collect_host as _ch
    s = TpuSession({})
    fact_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                            T.StructField("g", T.StringType(), True),
                            T.StructField("v", T.LongType(), True)])
    dim_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                           T.StructField("name", T.StringType(), True)])
    fact = s.from_pydict({"k": [1, 2, 3, 4] * 10, "g": ["a"] * 40,
                          "v": list(range(40))}, fact_schema,
                         partitions=2, rows_per_batch=8)
    dim = s.from_pydict({"k": [1, 2, 9], "name": ["x", "y", "z"]},
                        dim_schema)
    out = fact.join(dim, on="k", how="right")
    dev = sorted(out.collect(), key=str)
    ov, meta = out._overridden(quiet=True)
    host = sorted(_ch(meta.exec_node, s.conf), key=str)
    assert dev == host
    # k=9 is unmatched: null-extended fact side, dim columns present
    assert (None, None, None, 9, "z") in dev
    # every matched row keeps fact columns aligned (g is the string)
    matched = [r for r in dev if r[0] is not None]
    assert all(r[1] == "a" and r[4] in ("x", "y") for r in matched)
    assert len(matched) == 20

"""I/O layer tests: scan modes, pushdown, writers, round-trips.

Mirrors the reference's parquet/orc/csv round-trip integration tests
(integration_tests parquet_test.py, orc_test.py, csv_test.py;
write path _assert_gpu_and_cpu_writes_are_equal, asserts.py:189).
"""
import datetime
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import (ExecCtx, FilterExec, HashAggregateExec,
                                   ProjectExec, collect_device, collect_host)
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.io import (CsvScanExec, OrcScanExec, ParquetScanExec,
                                 write_csv, write_orc, write_parquet)
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal, _sort_key
from spark_rapids_tpu.conf import TpuConf


@pytest.fixture
def pq_dir(tmp_path, rng):
    """Directory of several small parquet files with mixed types+nulls."""
    d = tmp_path / "data"
    d.mkdir()
    for i in range(4):
        n = 50 + i * 10
        tbl = pa.table({
            "a": pa.array([int(x) if x % 7 else None
                           for x in rng.integers(0, 100, n)],
                          type=pa.int32()),
            "b": pa.array(rng.random(n), type=pa.float64()),
            "s": pa.array([f"v{x}" if x % 5 else None
                           for x in rng.integers(0, 40, n)]),
            "d": pa.array([datetime.date(2020, 1, 1)
                           + datetime.timedelta(days=int(x))
                           for x in rng.integers(0, 365, n)]),
        })
        pq.write_table(tbl, d / f"f{i}.parquet")
    return str(d)


@pytest.mark.parametrize("mode", ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_parquet_scan_modes(pq_dir, mode):
    conf = TpuConf({"spark.rapids.sql.format.parquet.reader.type": mode})
    scan = ParquetScanExec(pq_dir, partitions=2)
    rows = assert_tpu_and_cpu_equal(scan, conf=conf)
    assert len(rows) == 50 + 60 + 70 + 80


def test_parquet_column_pruning(pq_dir):
    scan = ParquetScanExec(pq_dir, columns=["s", "a"])
    assert scan.output_schema.names == ["s", "a"]
    assert_tpu_and_cpu_equal(scan)


def test_parquet_pushdown(pq_dir):
    scan = ParquetScanExec(pq_dir, pushdown=(col("a") > lit(50)))
    rows = assert_tpu_and_cpu_equal(scan)
    assert all(r[0] is not None and r[0] > 50 for r in rows)


def test_parquet_scan_query(pq_dir):
    scan = ParquetScanExec(pq_dir)
    plan = HashAggregateExec(
        [col("s")],
        [col("s"), Sum(col("a")).alias("sa"), CountStar().alias("c")],
        FilterExec(col("b") < lit(0.5), scan))
    assert_tpu_and_cpu_equal(plan)


def test_parquet_write_roundtrip(pq_dir, tmp_path):
    scan = ParquetScanExec(pq_dir)
    out = str(tmp_path / "out_pq")
    files = write_parquet(ProjectExec(
        [col("a"), (col("b") * 2.0).alias("b2"), col("s"), col("d")], scan),
        out)
    assert files and os.path.exists(os.path.join(out, "_SUCCESS"))
    back = ParquetScanExec(out)
    assert_tpu_and_cpu_equal(back)
    # device-written output == host-written output
    a = sorted(collect_host(back), key=_sort_key)
    out2 = str(tmp_path / "out_pq2")
    write_parquet(ProjectExec(
        [col("a"), (col("b") * 2.0).alias("b2"), col("s"), col("d")], scan),
        out2, ctx=ExecCtx(backend="host"))
    b = sorted(collect_host(ParquetScanExec(out2)), key=_sort_key)
    assert a == b


def test_orc_roundtrip(pq_dir, tmp_path):
    scan = ParquetScanExec(pq_dir, columns=["a", "b", "s"])
    out = str(tmp_path / "out_orc")
    write_orc(scan, out)
    back = OrcScanExec(out)
    assert_tpu_and_cpu_equal(back)


def test_csv_roundtrip(pq_dir, tmp_path):
    scan = ParquetScanExec(pq_dir, columns=["a", "b"])
    out = str(tmp_path / "out_csv")
    write_csv(scan, out)
    schema = T.Schema([T.StructField("a", T.IntegerType()),
                       T.StructField("b", T.DoubleType())])
    back = CsvScanExec(out, schema=schema)
    assert_tpu_and_cpu_equal(back)
    assert len(collect_host(back)) == len(collect_host(scan))


def test_pushdown_literal_on_left(pq_dir):
    scan = ParquetScanExec(pq_dir, pushdown=(lit(50) < col("a")))
    rows = assert_tpu_and_cpu_equal(scan)
    assert all(r[0] > 50 for r in rows)


def test_unpushable_predicate_rejected(pq_dir):
    from spark_rapids_tpu.expr.predicates import Not
    with pytest.raises(ValueError, match="not pushable"):
        ParquetScanExec(pq_dir, pushdown=Not(col("a") > lit(0)))


def test_orc_column_order(pq_dir, tmp_path):
    scan = ParquetScanExec(pq_dir, columns=["a", "s"])
    out = str(tmp_path / "orc2")
    write_orc(scan, out)
    back = OrcScanExec(out, columns=["s", "a"])
    assert back.output_schema.names == ["s", "a"]
    assert_tpu_and_cpu_equal(back)


def test_empty_write_keeps_schema(pq_dir, tmp_path):
    scan = ParquetScanExec(pq_dir, columns=["a", "b"])
    empty = FilterExec(col("a") > lit(10**6), scan)
    out = str(tmp_path / "empty_out")
    write_parquet(empty, out)
    back = ParquetScanExec(out)
    assert back.output_schema.names == ["a", "b"]
    assert collect_host(back) == []


# ---------------------------------------------------------------------------
# regression tests: review findings on the scan layer
# ---------------------------------------------------------------------------

def test_orc_csv_pushdown_is_applied(tmp_path, rng):
    import pyarrow.orc as orc
    n = 200
    tbl = pa.table({"a": pa.array(rng.integers(0, 100, n), type=pa.int32()),
                    "b": pa.array(rng.random(n))})
    orc.write_table(tbl, str(tmp_path / "t.orc"))
    import pyarrow.csv as pc
    pc.write_csv(tbl, str(tmp_path / "t.csv"))
    want = sorted(r for r in zip(*[c.to_pylist() for c in tbl.columns])
                  if r[0] > 50)
    for scan in (OrcScanExec(str(tmp_path / "t.orc"),
                             pushdown=col("a") > lit(50)),
                 CsvScanExec(str(tmp_path / "t.csv"),
                             pushdown=col("a") > lit(50))):
        got = sorted(collect_host(scan))
        assert [r[0] for r in got] == [r[0] for r in want]
        got_d = sorted(collect_device(scan))
        assert [r[0] for r in got_d] == [r[0] for r in want]


def test_csv_headerless_without_schema(tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("1,foo\n2,bar\n3,baz\n")
    scan = CsvScanExec(str(p), header=False)
    rows = collect_host(scan)
    assert len(rows) == 3  # first row must NOT be eaten as a header
    assert rows[0] == (1, "foo")


def test_coalescing_with_empty_part(tmp_path, rng):
    import pyarrow.orc as orc
    d = tmp_path / "orcs"
    d.mkdir()
    full = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
    empty = full.slice(0, 0)
    orc.write_table(full, str(d / "p0.orc"))
    orc.write_table(empty, str(d / "p1.orc"))
    conf = TpuConf({"spark.rapids.sql.format.orc.reader.type": "COALESCING"})
    rows = collect_host(OrcScanExec(str(d), partitions=1), conf=conf)
    assert sorted(rows) == [(1,), (2,), (3,)]


def test_batch_rows_honored_per_mode(pq_dir):
    for mode in ("PERFILE", "MULTITHREADED"):
        conf = TpuConf({
            "spark.rapids.sql.format.parquet.reader.type": mode,
            "spark.rapids.sql.reader.batchRows": 16,
        })
        scan = ParquetScanExec(pq_dir)
        ctx = ExecCtx(backend="host", conf=conf)
        for pid in range(scan.num_partitions(ctx)):
            for b in scan.partition_iter(ctx, pid):
                assert b.num_rows <= 16


def test_coalescing_merges_small_files(tmp_path):
    d = tmp_path / "many"
    d.mkdir()
    for i in range(6):
        pq.write_table(
            pa.table({"a": pa.array(list(range(i * 10, i * 10 + 10)),
                                    type=pa.int64())}),
            d / f"f{i}.parquet")
    conf = TpuConf({
        "spark.rapids.sql.format.parquet.reader.type": "COALESCING"})
    scan = ParquetScanExec(str(d), partitions=1)
    ctx = ExecCtx(backend="host", conf=conf)
    batches = list(scan.partition_iter(ctx, 0))
    assert len(batches) == 1 and batches[0].num_rows == 60


def test_reader_batch_size_bytes_cap(pq_dir):
    """reader.batchSizeBytes converts to a row cap via the schema width
    estimate (reference maxReadBatchSizeBytes, RapidsConf.scala:378)."""
    from spark_rapids_tpu.io.scan import _effective_batch_rows
    scan = ParquetScanExec(pq_dir)
    wide = _effective_batch_rows(scan.output_schema, {})
    tight = _effective_batch_rows(
        scan.output_schema, {"spark.rapids.sql.reader.batchSizeBytes": 4096})
    assert tight < wide
    assert tight >= 256
    conf = TpuConf({"spark.rapids.sql.reader.batchSizeBytes": 4096})
    ctx = ExecCtx(backend="host", conf=conf)
    for pid in range(scan.num_partitions(ctx)):
        for b in scan.partition_iter(ctx, pid):
            assert b.num_rows <= tight


def test_orc_stripe_pruning(tmp_path):
    """Stripes whose statistics cannot match the pushdown predicate are
    skipped without being read, with identical results (reference
    SearchArgument stripe selection, GpuOrcScan.scala:240-245,327-360)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.orc as orc

    n = 200_000
    path = str(tmp_path / "sorted.orc")
    t = pa.table({
        "a": np.arange(n, dtype=np.int64),
        "d": np.arange(n, dtype=np.float64) * 0.5,
        "s": pa.array([f"k{i // 1000:04d}" for i in range(n)]),
    })
    # small stripes so the file has many; values sorted => tight stats
    orc.write_table(t, path, stripe_size=256 * 1024)
    assert orc.ORCFile(path).nstripes > 3

    # int predicate hitting a narrow tail range
    pruned = OrcScanExec(path, pushdown=(col("a") >= lit(n - 100)))
    rows = assert_tpu_and_cpu_equal(pruned)
    assert len(rows) == 100
    assert pruned.stripes_skipped > 0

    # string-statistics pruning
    sp = OrcScanExec(path, pushdown=(col("s") == lit("k0000")))
    rows = collect_host(sp)
    assert len(rows) == 1000
    assert sp.stripes_skipped > 0

    # double-statistics pruning, literal on the left
    dp = OrcScanExec(path, pushdown=(lit(2.0) > col("d")))
    rows = collect_host(dp)
    assert len(rows) == 4
    assert dp.stripes_skipped > 0

    # predicate matching everything must skip nothing and lose nothing
    keep = OrcScanExec(path, pushdown=(col("a") >= lit(0)))
    assert len(collect_host(keep)) == n
    assert keep.stripes_skipped == 0


def test_orc_stripe_stats_parser(tmp_path):
    """orc_meta reads per-stripe min/max that bracket the real data."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.orc as orc
    from spark_rapids_tpu.io import orc_meta

    n = 100_000
    path = str(tmp_path / "stats.orc")
    vals = np.arange(n, dtype=np.int64)
    orc.write_table(pa.table({"a": vals}), path, stripe_size=128 * 1024)
    stats = orc_meta.stripe_column_stats(path)
    assert stats is not None
    f = orc.ORCFile(path)
    assert len(stats) == f.nstripes
    seen = 0
    for st in stats:
        # flattened col 0 = root struct; col 1 = "a"
        a = st[1]
        assert a["min"] == seen
        seen += a["n"]
        assert a["max"] == seen - 1
        assert a["has_null"] is False
    assert seen == n


def test_shared_scan_single_decode_and_release(pq_dir):
    """A scan marked share_output decodes ONCE per partition, every
    consumer sees the same rows, and the last consumer releases the
    parked catalog entries (formerly leaked until catalog close —
    q28-style plans accumulated every shared table in the spill
    tiers).  Result cache OFF: this pins the catalog-parking fallback
    path; the cache-routed path is pinned separately below."""
    from spark_rapids_tpu.exec.core import device_to_host
    scan = ParquetScanExec(pq_dir, partitions=2)
    scan.share_output = True
    scan.share_consumers = 3
    conf = TpuConf({"spark.rapids.sql.resultCache.enabled": "false"})
    with ExecCtx(backend="device", conf=conf) as ctx:
        baseline = len(ctx.catalog._entries)
        rows = []
        for consumer in range(3):
            for pid in range(scan.num_partitions(ctx)):
                got = []
                for b in scan.partition_iter(ctx, pid):
                    got.extend(device_to_host(b).to_rows())
                rows.append(sorted(got, key=_sort_key))
            if consumer < 2:
                # parked entries still registered for later consumers
                assert len(ctx.catalog._entries) > baseline
                assert any(k[0] == "scan_share" for k in ctx.cache
                           if isinstance(k, tuple))
        # last consumer closed the parked entries and dropped the cache
        assert len(ctx.catalog._entries) == baseline
        assert not any(k[0] == "scan_share" for k in ctx.cache
                       if isinstance(k, tuple))
    # all three consumers read identical data
    assert rows[0:2] == rows[2:4] == rows[4:6]
    assert sum(len(r) for r in rows[0:2]) == 50 + 60 + 70 + 80


def test_shared_scan_routes_through_fragment_cache(pq_dir):
    """With the result cache ON (the default), a shared scan's
    materialization lives in the process-wide fragment cache instead of
    the per-query catalog: one decode per partition, later consumers
    are fragment hits, nothing is parked in the catalog, and no
    consumer pin is left behind after the drains."""
    from spark_rapids_tpu.exec.core import device_to_host
    from spark_rapids_tpu.exec.result_cache import get_result_cache
    from spark_rapids_tpu.obs.registry import get_registry
    scan = ParquetScanExec(pq_dir, partitions=2)
    scan.share_output = True
    scan.share_consumers = 3
    before = get_registry().snapshot()
    with ExecCtx(backend="device") as ctx:
        baseline = len(ctx.catalog._entries)
        rows = []
        for _consumer in range(3):
            for pid in range(scan.num_partitions(ctx)):
                got = []
                for b in scan.partition_iter(ctx, pid):
                    got.extend(device_to_host(b).to_rows())
                rows.append(sorted(got, key=_sort_key))
            # the shared table is cache-resident, never catalog-parked
            assert len(ctx.catalog._entries) == baseline
            assert not any(k[0] == "scan_share" for k in ctx.cache
                           if isinstance(k, tuple))
    moved = get_registry().delta(before)["counters"]
    assert moved.get("result_cache_fragment_misses", 0) == 2, moved
    assert moved.get("result_cache_fragment_hits", 0) == 4, moved
    assert rows[0:2] == rows[2:4] == rows[4:6]
    assert sum(len(r) for r in rows[0:2]) == 50 + 60 + 70 + 80
    cache = get_result_cache()
    with cache._lock:
        pinned = [e.key for e in cache._entries.values() if e.consumers > 0]
    assert not pinned, f"consumer pins leaked: {pinned}"


def test_shared_scan_planner_counts_consumers(pq_dir):
    """The planner marks duplicate-fingerprint scans shared AND records
    the consumer count that drives the release."""
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({})
    df = s.read_parquet(pq_dir, columns=["a", "b"])
    agg1 = df.group_by("a").agg(Sum(col("b")))
    agg2 = df.where(col("a") > lit(50)).group_by("a").agg(Sum(col("b")))
    u = agg1.union(agg2)
    ov, meta = u._overridden(quiet=True)

    def scans(n, acc):
        if isinstance(n, ParquetScanExec):
            acc.append(n)
        for c in n.children:
            scans(c, acc)
        return acc

    marked = [sc for sc in scans(meta.exec_node, [])
              if getattr(sc, "share_output", False)]
    assert marked, "duplicate scans were not marked shared"
    assert all(sc.share_consumers >= 2 for sc in marked)
    # end to end: results match the host oracle (floats tolerate
    # summation-order noise between streaming and oracle aggregation)
    import math
    got = sorted(u.collect(), key=_sort_key)
    want = sorted(collect_host(meta.exec_node, s.conf), key=_sort_key)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for gc, wc in zip(g, w):
            if isinstance(gc, float):
                assert math.isclose(gc, wc, rel_tol=1e-9)
            else:
                assert gc == wc

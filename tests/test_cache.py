"""df.cache() columnar caching + runtime fallback conf.

Reference: ParquetCachedBatchSerializer (shims/spark310, SURVEY §5.4)
— df.cache() as compressed columnar blobs — and the engine's opt-in
runtime host fallback (beyond the reference's plan-time-only fallback).
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([T.StructField("k", T.IntegerType()),
                   T.StructField("v", T.DoubleType()),
                   T.StructField("s", T.StringType())])


def _df(s, n=150):
    rng = np.random.default_rng(9)
    return s.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 8, n)],
         "v": [None if i % 11 == 5 else float(i) for i in range(n)],
         "s": [None if i % 13 == 6 else f"x{i%19}" for i in range(n)]},
        SCHEMA, partitions=3, rows_per_batch=25)


@pytest.mark.parametrize("codec", ["none", "lz4", "zstd"])
def test_cache_roundtrip_both_backends(codec):
    if codec == "zstd":
        pytest.importorskip("zstandard")
    s = TpuSession({"spark.rapids.sql.cache.compression.codec": codec})
    base = _df(s).where(col("k") < lit(6))
    cached = base.cache()
    dev = sorted(cached.collect(), key=str)
    want = sorted(base.collect(), key=str)
    assert dev == want and len(dev) > 0
    ov, meta = cached._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert host == want


def test_cache_materializes_once_and_unpersists():
    from spark_rapids_tpu.exec.cache_exec import CachedScanExec
    s = TpuSession({})
    cached = _df(s).cache()
    node = cached._plan.exec_node
    assert isinstance(node, CachedScanExec)
    assert not node.is_materialized        # lazy until first use
    cached.collect()
    assert node.is_materialized
    blobs_id = id(node._blobs)
    cached.collect()
    assert id(node._blobs) == blobs_id      # served from cache, not rerun
    assert node.metrics["cached_bytes"] > 0
    cached.unpersist()
    assert not node.is_materialized
    assert len(cached.collect()) == 150     # re-materializes on demand


def test_cache_downstream_query():
    s = TpuSession({})
    cached = _df(s).cache()
    out = cached.group_by("k").agg(Sum(col("v")).alias("sv"))
    dev = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    assert dev == sorted(collect_host(meta.exec_node, s.conf))
    assert "CachedScanExec" in out.explain()


def test_runtime_fallback_on_device_error():
    """With fallbackOnDeviceError, a device runtime failure re-runs on
    the host engine with a warning; without it, the error propagates."""
    from spark_rapids_tpu.exec import basic as basic_mod

    class BoomExec(basic_mod.LocalScanExec):
        def partition_iter(self, ctx, pid):
            if ctx.is_device:
                raise RuntimeError("device exploded")
            yield from super().partition_iter.__wrapped__(self, ctx, pid)

    import spark_rapids_tpu.plan.logical as L
    s = TpuSession({"spark.rapids.sql.fallbackOnDeviceError": True})
    boom = BoomExec.from_pydict({"v": [1, 2, 3]},
                                T.Schema([T.StructField("v",
                                                        T.LongType())]))
    boom.__class__ = BoomExec
    df_ok = s.from_pydict({"v": [1, 2, 3]},
                          T.Schema([T.StructField("v", T.LongType())]))
    from spark_rapids_tpu.session import DataFrame
    df = DataFrame(s, L.Scan(boom))
    with pytest.warns(RuntimeWarning, match="device execution failed"):
        assert sorted(df.collect()) == [(1,), (2,), (3,)]
    s2 = TpuSession({})
    df2 = DataFrame(s2, L.Scan(boom))
    with pytest.raises(RuntimeError, match="device exploded"):
        df2.collect()


def test_cache_plan_time_does_not_materialize():
    """explain()/planning must not execute the source (review finding:
    num_partitions used to force materialization at plan time)."""
    from spark_rapids_tpu.exec.cache_exec import CachedScanExec
    s = TpuSession({})
    cached = _df(s).cache()
    out = cached.group_by("k").agg(Sum(col("v")).alias("sv"))
    _ = out.explain()
    node = cached._plan.exec_node
    assert isinstance(node, CachedScanExec)
    assert not node.is_materialized


def test_cache_mesh_source_partition_count():
    """Backend-dependent source partition counts (mesh execs) must not
    desync serving from the materialized blobs (review repro: host-first
    reads of a mesh-aggregated cache returned [] silently)."""
    s = TpuSession({"spark.rapids.tpu.mesh.deviceCount": 8})
    base = _df(s).group_by("k").agg(Sum(col("v")).alias("sv"))
    want = sorted(base.collect(), key=str)
    cached = base.cache()
    ov, meta = cached._overridden(quiet=True)
    # host-first read of a device-materialized cache
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert host == want and len(host) > 0
    dev = sorted(cached.collect(), key=str)
    assert dev == want

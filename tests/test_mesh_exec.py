"""Engine-level mesh shuffle/aggregation: DataFrame -> shard_map plan.

VERDICT r1 item 2: the mesh all-to-all data plane must be reachable from
the planner/exec layer.  These tests run real DataFrame queries with
``spark.rapids.tpu.mesh.deviceCount=8`` on the virtual 8-device CPU mesh
and compare against the host oracle (the reference's differential
pattern, asserts.py:290).
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import Average, CountStar, Max, Min, Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.session import TpuSession

MESH_CONF = {"spark.rapids.tpu.mesh.deviceCount": 8}

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("g", T.StringType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("f", T.DoubleType(), True),
])


def _data(rng, n=400, nkeys=17):
    return {
        "k": rng.integers(0, nkeys, n).astype(np.int32),
        "g": np.array([f"g{int(x) % 5}" for x in rng.integers(0, 50, n)],
                      dtype=object),
        "v": rng.integers(-1000, 1000, n).astype(np.int64),
        "f": rng.normal(size=n),
    }


def _sessions():
    return (TpuSession(MESH_CONF), TpuSession({}))


def _sorted_rows(rows):
    return sorted(rows, key=lambda r: tuple(
        (x is None, str(x)) for x in r))


def _assert_same(mesh_df, plain_df, approx_cols=()):
    got = _sorted_rows(mesh_df.collect())
    want = _sorted_rows(plain_df.collect())
    assert len(got) == len(want), (len(got), len(want))
    for rg, rw in zip(got, want):
        assert len(rg) == len(rw)
        for i, (a, b) in enumerate(zip(rg, rw)):
            if i in approx_cols and a is not None and b is not None:
                assert abs(a - b) <= 1e-9 * max(1.0, abs(b)), (rg, rw)
            else:
                assert a == b, (rg, rw)


def test_mesh_groupby_plan_uses_mesh_exec(rng):
    s, _ = _sessions()
    df = s.from_pydict(_data(rng), SCHEMA, partitions=4) \
        .group_by("k").agg(Sum(col("v")).alias("sv"))
    assert "MeshAggregateExec" in df.explain()


def test_mesh_groupby_matches_plain_engine(rng):
    data = _data(rng)
    sm, sp = _sessions()
    aggs = lambda: (Sum(col("v")).alias("sv"),  # noqa: E731
                    CountStar().alias("n"),
                    Min(col("v")).alias("mn"),
                    Max(col("f")).alias("mx"),
                    Average(col("f")).alias("av"))
    dfm = sm.from_pydict(data, SCHEMA, partitions=4).group_by("k").agg(*aggs())
    dfp = sp.from_pydict(data, SCHEMA, partitions=4).group_by("k").agg(*aggs())
    _assert_same(dfm, dfp, approx_cols=(4, 5))


def test_mesh_groupby_string_key(rng):
    data = _data(rng)
    sm, sp = _sessions()
    dfm = sm.from_pydict(data, SCHEMA, partitions=3) \
        .group_by("g").agg(Sum(col("v")).alias("sv"), CountStar().alias("n"))
    dfp = sp.from_pydict(data, SCHEMA, partitions=3) \
        .group_by("g").agg(Sum(col("v")).alias("sv"), CountStar().alias("n"))
    _assert_same(dfm, dfp)


def test_mesh_groupby_with_nulls_and_filter(rng):
    data = _data(rng)
    sm, sp = _sessions()

    def q(s):
        df = s.from_pydict(data, SCHEMA, partitions=4)
        return df.where(col("v") > 0).group_by("k").agg(
            Sum(col("v")).alias("sv"), CountStar().alias("n"))

    _assert_same(q(sm), q(sp))


def test_mesh_groupby_host_oracle_differential(rng):
    """Device mesh result vs the host backend of the SAME mesh plan."""
    from spark_rapids_tpu.exec.core import collect_host
    data = _data(rng)
    s = TpuSession(MESH_CONF)
    df = s.from_pydict(data, SCHEMA, partitions=4).group_by("k").agg(
        Sum(col("v")).alias("sv"), CountStar().alias("n"))
    dev = _sorted_rows(df.collect())
    _, meta = df._overridden(quiet=True)
    host = _sorted_rows(collect_host(meta.exec_node, s.conf))
    assert dev == host


def test_mesh_repartition_preserves_rows_and_colocates_keys(rng):
    data = _data(rng, n=300)
    s = TpuSession(MESH_CONF)
    df = s.from_pydict(data, SCHEMA, partitions=4).repartition(8, "k")
    assert "MeshExchangeExec" in df.explain()
    rows = df.collect()
    plain = TpuSession({}).from_pydict(data, SCHEMA, partitions=4).collect()
    assert _sorted_rows(rows) == _sorted_rows(plain)

    # key colocation: execute partition-wise and check key disjointness
    from spark_rapids_tpu.exec.core import ExecCtx, device_to_host
    _, meta = df._overridden(quiet=True)
    ctx = ExecCtx(backend="device", conf=s.conf)
    ex = meta.exec_node
    key_sets = []
    for pid in range(ex.num_partitions(ctx)):
        ks = set()
        for b in ex.partition_iter(ctx, pid):
            hb = device_to_host(b)
            ks.update(hb.columns[0].to_list())
        key_sets.append(ks)
    for i in range(len(key_sets)):
        for j in range(i + 1, len(key_sets)):
            assert not (key_sets[i] & key_sets[j] - {None})


def test_mesh_grand_aggregate(rng):
    data = _data(rng)
    sm, sp = _sessions()
    dfm = sm.from_pydict(data, SCHEMA, partitions=4).agg(
        Sum(col("v")).alias("sv"), CountStar().alias("n"))
    dfp = sp.from_pydict(data, SCHEMA, partitions=4).agg(
        Sum(col("v")).alias("sv"), CountStar().alias("n"))
    # grand agg: no group keys -> planner keeps complete mode (no mesh);
    # both engines must agree regardless
    _assert_same(dfm, dfp)


def test_mesh_exchange_arbitrary_partition_count(rng):
    """Round-3: repartition counts != deviceCount still ride the mesh
    (rows route to device pid % mesh; each device serves its subset)."""
    mesh_s, plain_s = _sessions()
    data = _data(rng)
    for n in (3, 8, 13):
        mesh_df = mesh_s.from_pydict(data, SCHEMA, 2, 100).repartition(n, "k")
        plain_df = plain_s.from_pydict(data, SCHEMA, 2, 100).repartition(n, "k")
        ov, meta = mesh_df._overridden(quiet=True)
        assert "MeshExchangeExec" in meta.exec_node.node_desc()
        assert meta.exec_node.num_partitions(None) == n
        _assert_same(mesh_df, plain_df, approx_cols=(3,))


def test_place_shards_no_central_gather():
    """place_shards groups batches per device; union of shard rows ==
    input rows, and no shard sees the full concatenation."""
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.exec.core import ExecCtx, device_to_host
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.exec.mesh_exec import place_shards
    data = {"k": list(range(100)), "s": [f"v{i%7}" for i in range(100)]}
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("s", T.StringType())])
    scan = LocalScanExec.from_pydict(data, schema, 1, 25)  # 4 batches
    ctx = ExecCtx(backend="device")
    batches = [b for b in scan.partition_iter(ctx, 0)]
    shards = place_shards(batches, 4)
    assert len(shards) == 4
    caps = {s.capacity for s in shards}
    assert len(caps) == 1               # uniform capacity
    got = []
    for sh in shards:
        hb = device_to_host(sh)
        got.extend(zip(*[c.to_list() for c in hb.columns]))
    assert sorted(got) == sorted(zip(data["k"], data["s"]))
    # no shard was handed every batch (the old central-concat shape)
    assert max(sh.host_num_rows() for sh in shards) < 100


def _dim_df(s):
    dim_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                           T.StructField("name", T.StringType(), True)])
    return s.from_pydict(
        {"k": list(range(0, 17, 2)),
         "name": [f"n{i}" for i in range(0, 17, 2)]},
        dim_schema, partitions=1)


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti", "right"])
def test_mesh_join_matches_oracle(rng, how):
    """MeshJoinExec: replicated build + per-device probe shards, every
    join type, vs the host oracle."""
    from spark_rapids_tpu.exec.core import collect_host
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=4,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how=how)
    assert "MeshJoinExec" in out.explain()
    dev = _sorted_rows(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = _sorted_rows(collect_host(meta.exec_node, sm.conf))
    assert dev == host and len(dev) > 0


def test_mesh_join_outputs_per_device(rng):
    """Probe outputs land on distinct mesh devices (no central probe)."""
    import jax
    from spark_rapids_tpu.exec.core import ExecCtx
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=4,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how="inner")
    ov, meta = out._overridden(quiet=True)
    with ExecCtx(backend="device", conf=sm.conf) as ctx:
        node = meta.exec_node
        devs = set()
        for pid in range(node.num_partitions(ctx)):
            for b in node.partition_iter(ctx, pid):
                d = list(b.columns[0].data.devices())[0]
                devs.add(d)
        assert len(devs) > 1, f"all probe output on one device: {devs}"


def test_mesh_join_then_mesh_aggregate(rng):
    """The flagship shape: mesh join feeding a mesh group-by (q6-like
    scan -> join -> agg end to end under the mesh conf)."""
    from spark_rapids_tpu.exec.core import collect_host
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=4,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how="inner") \
        .group_by("name").agg(Sum(col("v")).alias("sv"),
                              CountStar().alias("cnt"))
    plan = out.explain()
    assert "MeshJoinExec" in plan and "MeshAggregateExec" in plan
    dev = _sorted_rows(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = _sorted_rows(collect_host(meta.exec_node, sm.conf))
    assert dev == host and len(dev) > 0


def test_mesh_full_join_stays_in_process(rng):
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=2,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how="full")
    plan = out.explain()
    assert "MeshJoinExec" not in plan and "JoinExec" in plan


def test_mesh_grand_aggregate_over_join(rng):
    """q96 shape under mesh: joins feeding a GRAND aggregate (no group
    keys) must lower to the mesh program — per-device join outputs in
    the single-device complete path mixed devices (matrix finding)."""
    from spark_rapids_tpu.exec.core import collect_host
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=4,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how="inner") \
        .agg(CountStar().alias("cnt"), Sum(col("v")).alias("sv"))
    plan = out.explain()
    assert "MeshAggregateExec" in plan
    dev = out.collect()
    ov, meta = out._overridden(quiet=True)
    assert dev == collect_host(meta.exec_node, sm.conf)
    assert dev[0][0] > 0


def test_mesh_join_feeding_non_mesh_consumer(rng):
    """Review repro: a non-mesh device operator above mesh outputs (a
    full join stays in-process) must not mix devices inside its jitted
    programs — the planner aligns mesh outputs at the boundary."""
    from spark_rapids_tpu.exec.core import collect_host
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=4,
                          rows_per_batch=64)
    dim2_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                            T.StructField("w", T.DoubleType(), True)])
    dim2 = sm.from_pydict({"k": [0, 1, 2, 99],
                           "w": [0.5, 1.5, 2.5, 9.9]}, dim2_schema)
    out = fact.join(_dim_df(sm), on="k", how="inner") \
        .join(dim2, on="k", how="full")
    plan = out.explain()
    assert "MeshJoinExec" in plan and "JoinExec[full" in plan
    dev = _sorted_rows(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = _sorted_rows(collect_host(meta.exec_node, sm.conf))
    assert dev == host and len(dev) > 0


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_mesh_join_partitioned_matches_oracle(rng, how):
    """Partitioned mesh join (VERDICT r3 item 5): threshold 0 forces the
    all-to-all-both-sides path (GpuShuffledHashJoinExec.scala:162
    analog); result must equal the host oracle for every join type."""
    from spark_rapids_tpu.exec.core import collect_host
    sm = TpuSession({**MESH_CONF,
                     "spark.rapids.tpu.mesh.join.buildThresholdBytes": 0})
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=4,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how=how)
    assert "MeshJoinExec" in out.explain()
    dev = _sorted_rows(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = _sorted_rows(collect_host(meta.exec_node, sm.conf))
    assert dev == host and len(dev) > 0


def test_mesh_join_partitioned_large_build(rng):
    """Build side larger than one device's fair shard still joins
    correctly: every build row is present exactly once across the mesh
    after the all-to-all (no replication)."""
    from spark_rapids_tpu.exec.core import collect_host
    sm = TpuSession({**MESH_CONF,
                     "spark.rapids.tpu.mesh.join.buildThresholdBytes": 0})
    n = 3000   # build side BIGGER than the stream side
    build_schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                             T.StructField("w", T.LongType(), True)])
    build = sm.from_pydict(
        {"k": rng.integers(0, 97, n).astype(np.int32),
         "w": rng.integers(0, 10**6, n).astype(np.int64)},
        build_schema, partitions=4, rows_per_batch=256)
    probe = sm.from_pydict(_data(rng, n=300, nkeys=97), SCHEMA,
                           partitions=2, rows_per_batch=64)
    out = probe.join(build, on="k", how="inner") \
        .group_by("k").agg(Sum(col("w")).alias("sw"),
                           CountStar().alias("cnt"))
    assert "MeshJoinExec" in out.explain()
    dev = _sorted_rows(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = _sorted_rows(collect_host(meta.exec_node, sm.conf))
    assert dev == host and len(dev) > 0


def test_mesh_join_threshold_keeps_replicated(rng):
    """A tiny build under the default threshold stays on the replicated
    path (no exchange nodes execute for the build side)."""
    from spark_rapids_tpu.exec.core import ExecCtx
    sm, _ = _sessions()
    fact = sm.from_pydict(_data(rng), SCHEMA, partitions=2,
                          rows_per_batch=64)
    out = fact.join(_dim_df(sm), on="k", how="inner")
    ov, meta = out._overridden(quiet=True)
    node = meta.exec_node
    from spark_rapids_tpu.exec.mesh_exec import MeshJoinExec
    while not isinstance(node, MeshJoinExec):
        node = node.children[0]
    with ExecCtx(backend="device", conf=sm.conf) as ctx:
        list(node.partition_iter(ctx, 0))
        assert node._use_partitioned(ctx) is False
        # neither exchange computed its outputs (replicated path only)
        for ex in node._exchanges:
            assert ("meshex", id(ex), ctx.backend) not in ctx.cache
